"""Property-style sweep: the zero-pad invariant must hold for every operation
on shapes that don't divide the mesh grid (the reference instead threads
ragged edge blocks through every operator — DenseVecMatrix.scala:1103-1107;
here a single invariant carries that correctness)."""

import numpy as np
import pytest

import marlin_tpu as mt

SHAPES = [(7, 5), (9, 11), (1, 3), (13, 8), (8, 13)]


def _pads_zero(m: mt.DenseMatrix) -> bool:
    if not m._padded:
        return True
    data = np.asarray(m.data)
    rows, cols = m.shape
    return (data[rows:, :] == 0).all() and (data[:, cols:] == 0).all()


@pytest.mark.parametrize("shape", SHAPES)
@pytest.mark.parametrize("klass", [mt.DenseVecMatrix, mt.BlockMatrix])
def test_all_ops_preserve_invariant(mesh, shape, klass):
    rng = np.random.default_rng(hash(shape) % 2**31)
    a_np = rng.standard_normal(shape).astype(np.float32) + 1.5
    b_np = rng.standard_normal(shape).astype(np.float32) + 1.5
    a = klass.from_array(a_np, mesh)
    b = klass.from_array(b_np, mesh)

    cases = {
        "add_s": (a.add(3.0), a_np + 3.0),
        "sub_s": (a.subtract(2.0), a_np - 2.0),
        "sub_by": (a.subtract_by(2.0), 2.0 - a_np),
        "mul_s": (a.multiply(2.0), a_np * 2.0),
        "div_s": (a.divide(2.0), a_np / 2.0),
        "div_by": (a.divide_by(2.0), 2.0 / a_np),
        "add_m": (a.add(b), a_np + b_np),
        "sub_m": (a.subtract(b), a_np - b_np),
        "div_m": (a.divide(b), a_np / b_np),
        "dot": (a.dot_product(b), a_np * b_np),
    }
    for name, (out, expected) in cases.items():
        assert _pads_zero(out), f"{name} broke the pad invariant for {shape}"
        np.testing.assert_allclose(out.to_numpy(), expected, rtol=1e-3, atol=1e-3,
                                   err_msg=name)
        # the invariant is what makes sums correct without masking
        assert float(out.sum()) == pytest.approx(float(expected.sum()), rel=1e-3), name


@pytest.mark.parametrize("shape", SHAPES)
def test_matmul_chain_uneven(mesh, shape):
    rng = np.random.default_rng(0)
    m, n = shape
    a_np = rng.standard_normal((m, n)).astype(np.float32)
    b_np = rng.standard_normal((n, m)).astype(np.float32)
    a = mt.BlockMatrix.from_array(a_np, mesh)
    b = mt.BlockMatrix.from_array(b_np, mesh)
    c = a.multiply(b)  # (m, m)
    assert _pads_zero(c)
    d = c.multiply(a)  # (m, n) — chained result reused as operand
    np.testing.assert_allclose(d.to_numpy(), (a_np @ b_np) @ a_np, rtol=1e-3, atol=1e-3)


def test_models_namespace():
    from marlin_tpu import models

    assert hasattr(models, "NeuralNetwork")
    assert hasattr(models, "als_run")
    assert hasattr(models, "pagerank")
    assert hasattr(models, "logistic_regression")
