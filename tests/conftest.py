"""Shared fixtures: the "fake cluster" meshes and golden 4×4 matrices.

Mirrors the reference's test harness (SURVEY.md §4): ``LocalSparkContext``
(a local[2] SparkContext) becomes an 8-device CPU mesh; the fixed 4×4 matrix
from ``DistributedMatrixSuite`` (src/test/.../DistributedMatrixSuite.scala:15-32)
becomes NumPy goldens compared via ``to_numpy()`` against a NumPy oracle
(their pattern: compute distributed, ``toBreeze()``, compare vs Breeze).
"""

import contextlib
import threading
import time

import numpy as np
import pytest

import marlin_tpu as mt


# ---------------------------------------------------------------- compiles

class _CompileCount:
    def __init__(self):
        from marlin_tpu.obs import collectors

        self._start = collectors.compile_count()

    @property
    def count(self) -> int:
        from marlin_tpu.obs import collectors

        return collectors.compile_count() - self._start


@pytest.fixture()
def compile_count():
    """Count XLA compiles around a block — the reusable compile-bound guard
    (serving + prefetch suites): ``with compile_count() as c: ...;
    assert c.count <= bound``. Counts every backend compile in the process
    (any thread — serving workers included), so scope the block tightly and
    warm auxiliary one-time programs (PRNG key creation, dtype converts)
    before asserting an exact bound.

    The tally itself lives in the library now
    (``marlin_tpu.obs.collectors.install_compile_metrics`` — the
    jax.monitoring bridge that also feeds ``marlin_compile_total``), so
    production runs see the same signal this fixture guards in tests."""
    from marlin_tpu.obs import collectors

    collectors.install_compile_metrics()

    @contextlib.contextmanager
    def guard():
        yield _CompileCount()

    return guard


# worker-thread name prefixes owned by the library; each subsystem joins its
# workers on close (ChunkPrefetcher.close, ServeEngine.drain/close,
# MetricsServer.close, FleetController.close), so any survivor after a test
# is a leak in that test or that subsystem
_WORKER_PREFIXES = ("marlin-prefetch", "marlin-serve", "marlin-obs",
                    "marlin-fleet")


def _worker_threads():
    return [t for t in threading.enumerate()
            if t.name.startswith(_WORKER_PREFIXES)]


@pytest.fixture(autouse=True)
def _no_worker_thread_leaks():
    """No library worker may outlive its owner: prefetch producers join on
    pipeline close/exhaustion, serving workers join inside drain()/close().
    Mirrors the fault-registry leak check below. A short grace window absorbs
    workers mid-observation of their stop flag."""
    yield
    deadline = time.monotonic() + 2.0
    while _worker_threads() and time.monotonic() < deadline:
        time.sleep(0.01)
    leaked = _worker_threads()
    assert not leaked, f"worker thread(s) leaked across tests: {leaked}"


@pytest.fixture(autouse=True)
def _no_fault_leaks():
    """No injected fault may leak across tests: the chaos harness
    (marlin_tpu.utils.faults) auto-deregisters exhausted faults and tests use
    faults.injected(...) scoping, so a non-empty registry after a test is a
    bug in that test — fail it loudly, but clear first so the leak doesn't
    cascade into every later test."""
    from marlin_tpu.utils import faults

    yield
    leaked = faults.active()
    faults.clear()
    assert not leaked, f"injected fault(s) leaked across tests: {leaked}"


@pytest.fixture(scope="session")
def mesh():
    """2-D (2×4) mesh — the BlockMatrix grid."""
    return mt.create_mesh((2, 4))


@pytest.fixture(scope="session")
def row_mesh():
    """1-D (8×1) mesh — the DenseVecMatrix row layout."""
    return mt.create_mesh((8, 1))


@pytest.fixture()
def a4():
    # deliberately non-symmetric, non-singular
    return np.array(
        [
            [1.0, 2.0, 3.0, 4.0],
            [5.0, 6.0, 7.0, 8.0],
            [9.0, 10.0, 11.0, 13.0],
            [14.0, 15.0, 17.0, 16.0],
        ]
    )


@pytest.fixture()
def b4():
    return np.array(
        [
            [1.0, 1.0, 2.0, 0.0],
            [0.0, 3.0, 1.0, 1.0],
            [2.0, 0.0, 0.0, 1.0],
            [1.0, 1.0, 1.0, 2.0],
        ]
    )


def assert_close(mat, expected, tol=1e-4):
    np.testing.assert_allclose(np.asarray(mat.to_numpy() if hasattr(mat, "to_numpy") else mat),
                               expected, rtol=tol, atol=tol)
