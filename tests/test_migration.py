"""Cross-replica KV migration suite (serving/kvpool.py export/import,
serving/engine.py freeze/adopt, serving/router.py migrate-then-restart;
docs/serving.md "Migration protocol", docs/robustness.md).

The acceptance bars, bottom up:

- **Wire format**: a flipped byte or truncated blob ALWAYS raises
  :class:`MigrationCorruptError` on import — a silently-corrupt KV page
  decodes garbage forever, so the CRC frame is load-bearing, not
  decorative.
- **Re-dedup**: importing rows whose prompts share a prefix re-allocates
  through the normal :meth:`match_prefix` path, so shared pages collapse
  again on the target instead of arriving duplicated.
- **Exactly-once + bit-identity**: rows frozen mid-decode on one engine
  and adopted by another resume from their exact cursor — final outputs
  byte-equal an uninterrupted :func:`lm_generate`, every handle reaches
  one terminal Result, the admission reservation travels exactly once
  (:meth:`AdmissionQueue.adopt` on the target, release on the source).
- **Rotation without work loss**: ``Router.rolling_restart`` under
  continuous load drops nothing AND restarts nothing from token 0
  (``retries == 0`` — the PR 7 retry counter is exactly the token-0
  restart counter).
- **Chaos**: a fault on any ``serve.migrate`` leg (export / import /
  adopt / warm) degrades to the PR 7 retry path — every request still
  reaches one ok Result and :meth:`PagedKVPool.audit` stays clean on
  every replica (pages leak nowhere).
"""

import random
import threading
import time

import numpy as np
import pytest

import jax

from marlin_tpu.models import TransformerLM
from marlin_tpu.models.transformer import lm_generate
from marlin_tpu.obs import memledger
from marlin_tpu.obs.exposition import (kvpool_payload,
                                       register_kvpool_provider,
                                       unregister_kvpool_provider)
from marlin_tpu.serving import (
    STATUS_OK,
    PagedKVPool,
    Request,
    Router,
    ServeEngine,
)
from marlin_tpu.serving.engine import MigrationError
from marlin_tpu.serving.kvpool import MigrationCorruptError
from marlin_tpu.serving.request import AdmissionQueue
from marlin_tpu.utils import faults
from marlin_tpu.utils.faults import DelayFault, RaiseFault, Schedule

HEADS = 2
BUCKETS = ((8, 8), (16, 8))
PAGE_LEN = 4


@pytest.fixture(scope="module")
def params():
    return TransformerLM(vocab=32, d_model=16, heads=HEADS, layers=2,
                         seed=9).init_params()


def _engine(params, **kw):
    kw.setdefault("buckets", BUCKETS)
    kw.setdefault("max_batch", 4)
    kw.setdefault("queue_depth", 64)
    kw.setdefault("page_len", PAGE_LEN)
    kw.setdefault("num_pages", 256)
    kw.setdefault("paged", True)
    return ServeEngine(params, HEADS, **kw)


def _factory(params, **kw):
    def make():
        eng = _engine(params, **kw)
        # migration binds into live slots during rotation: an unwarmed
        # replacement would sit in first-traffic XLA compile for seconds
        # while the freeze deadline of the NEXT rotation ticks
        eng.warmup()
        return eng
    return make


def _ref(params, prompt, steps, heads=HEADS):
    prompt = np.asarray(prompt, np.int32)
    return np.asarray(lm_generate(
        params, prompt, jax.random.key(0), heads=heads,
        max_len=len(prompt) + steps, steps=steps)).tolist()


def _fill_pages(pool, pages, seed):
    """Write recognizable content into ``pages`` of ``pool``."""
    rng = np.random.default_rng(seed)
    host = pool._host_pages()
    for name in sorted(host):
        for half in (0, 1):
            arr = host[name][half]
            arr[pages] = rng.standard_normal(
                (len(pages),) + arr.shape[1:]).astype(arr.dtype)
    pool._flush_host(host)


def _page_bytes(pool, pages):
    host = pool._host_pages()
    return b"".join(np.ascontiguousarray(host[name][half][list(pages)])
                    .tobytes()
                    for name in sorted(host) for half in (0, 1))


# ------------------------------------------------------------- wire format


def test_export_import_roundtrip_and_corruption(params):
    """A row blob round-trips page contents exactly; a flipped byte or a
    truncation anywhere always raises MigrationCorruptError on import —
    and a failed import leaks no pages (audit stays clean)."""
    src = PagedKVPool(params, HEADS, num_pages=16, page_len=PAGE_LEN)
    prompt = np.arange(1, 13, dtype=np.int32)          # 12 toks = 3 pages
    pages = src.alloc(3)
    _fill_pages(src, pages, seed=42)
    row = {"rid": 7, "prompt": prompt.tolist(), "pages": pages,
           "pf_next": -1}
    blob = src.export_rows([row])

    dst = PagedKVPool(params, HEADS, num_pages=16, page_len=PAGE_LEN)
    out = dst.import_rows(blob)
    assert len(out) == 1 and out[0]["rid"] == 7
    assert _page_bytes(dst, out[0]["pages"]) == _page_bytes(src, pages)
    assert dst.audit()["ok"]

    # corruption: flip one byte in a page body, then in the meta chunk,
    # then truncate — every variant must raise, never import garbage
    fresh = PagedKVPool(params, HEADS, num_pages=16, page_len=PAGE_LEN)
    for cut in (len(blob) // 2, 40):
        bad = bytearray(blob)
        bad[cut] ^= 0xFF
        with pytest.raises(MigrationCorruptError):
            fresh.import_rows(bytes(bad))
    with pytest.raises(MigrationCorruptError):
        fresh.import_rows(blob[:-7])
    with pytest.raises(MigrationCorruptError):
        fresh.import_rows(b"")
    audit = fresh.audit()
    assert audit["ok"], audit["errors"]
    assert fresh.used_count() == 0      # failed imports released everything


def test_import_rededuplicates_shared_prefix(params):
    """Two exported rows sharing a prompt prefix collapse back onto shared
    pages on the target: the first row's completed prompt publishes its
    pages to the prefix cache, the second matches instead of importing."""
    src = PagedKVPool(params, HEADS, num_pages=32, page_len=PAGE_LEN)
    shared = list(range(1, 9))                          # 8 toks = 2 pages
    rows = []
    for rid, tail in enumerate(([9, 10, 11, 12], [13, 14, 15, 16])):
        pages = src.alloc(3)
        _fill_pages(src, pages, seed=rid)
        rows.append({"rid": rid, "prompt": shared + tail, "pages": pages,
                     "pf_next": -1})
    blob = src.export_rows(rows)

    dst = PagedKVPool(params, HEADS, num_pages=32, page_len=PAGE_LEN)
    out = dst.import_rows(blob)
    assert out[0]["n_shared"] == 0              # nothing cached yet
    assert out[1]["n_shared"] == 2              # the two full shared pages
    assert dst.hits > 0
    assert out[0]["pages"][:2] == out[1]["pages"][:2]
    assert out[0]["pages"][2] != out[1]["pages"][2]
    audit = dst.audit()
    assert audit["ok"], audit["errors"]


# ------------------------------------------------------- reservation unit


def test_admission_queue_adopt_carries_reservation():
    """adopt() charges a moved reservation unconditionally — depth, byte
    budget, and even closed state never bounce work that was ALREADY
    admitted on the source replica (rejecting it would drop it)."""
    q = AdmissionQueue(depth=1, budget_bytes=100)
    q.adopt(60)
    q.adopt(60)                 # over depth AND budget: still lands
    assert q.count == 2
    assert q.bytes_in_flight == 120
    q.close("draining")
    q.adopt(5)                  # closed: still lands
    assert q.count == 3
    q.release(60), q.release(60), q.release(5)
    assert q.count == 0 and q.bytes_in_flight == 0


# ------------------------------------------------------------------ audit


def test_audit_clean_and_seeded_violations(params):
    """audit() is quiet on a clean pool and names every seeded violation:
    the pinned dummy on the free list, a free page with a live refcount,
    and a leaked page (refcount 0, not on the free list)."""
    pool = PagedKVPool(params, HEADS, num_pages=8, page_len=PAGE_LEN)
    pages = pool.alloc(2)
    assert pool.audit()["ok"]

    pool._free.append(0)                              # dummy "freed"
    audit = pool.audit()
    assert not audit["ok"]
    assert any("dummy page 0" in e for e in audit["errors"])
    pool._free.remove(0)

    pool._free.append(pages[0])                       # freed but referenced
    audit = pool.audit()
    assert not audit["ok"]
    assert any(f"free page {pages[0]}" in e for e in audit["errors"])
    pool._free.remove(pages[0])

    pool._ref[pages[1]] = 0                           # leaked
    audit = pool.audit()
    assert not audit["ok"]
    assert any("leaked" in e for e in audit["errors"])
    pool._ref[pages[1]] = 1

    assert pool.audit()["ok"]


def test_debug_kvpool_endpoint(params):
    """A paged engine self-registers on /debug/kvpool: the payload is 200
    while its pool audits clean, 503 the moment any provider reports a
    violation, and the provider unregisters at close."""
    eng = _engine(params)
    try:
        code, payload = kvpool_payload()
        mine = [p for p in payload["pools"] if p["name"] == eng._name]
        assert code == 200 and payload["status"] == "ok"
        assert mine and mine[0]["ok"]

        register_kvpool_provider(
            "test-violated", lambda: {"ok": False, "errors": ["seeded"]})
        try:
            code, payload = kvpool_payload()
            assert code == 503 and payload["status"] == "violated"
        finally:
            unregister_kvpool_provider("test-violated")
    finally:
        eng.close()
    code, payload = kvpool_payload()
    assert all(p["name"] != eng._name for p in payload["pools"])


# ------------------------------------------------------- freeze and adopt


def test_freeze_adopt_midstream_bit_identical(params):
    """The tentpole invariant end to end: rows frozen MID-DECODE on engine
    A and adopted by engine B finish on B with outputs bit-identical to an
    uninterrupted reference decode, the queued backlog moves as-is, no
    request restarts from token 0 (retries == 0), and B's pool audits
    clean once drained."""
    memledger.reset_ledger()   # audit below must reflect THIS handoff only
    a, b = _engine(params), _engine(params)
    a.warmup(), b.warmup()
    steps = 8
    # hold A's worker 0.4s on its THIRD decode step: the freeze below
    # lands inside that window deterministically, with live rows that
    # have real decode progress behind them (a tiny warm model would
    # otherwise finish all 24 requests before any sleep-based freeze)
    with faults.injected("serve.decode_step",
                         DelayFault(seconds=0.4, times=1,
                                    schedule=Schedule(fire_on=[2]))):
        hs = [a.submit(Request(prompt=[3, 1 + i % 4, 2], steps=steps))
              for i in range(24)]
        time.sleep(0.1)
    try:
        frozen = a.freeze_rows()
        assert frozen is not None and frozen["blob"] is not None
        assert not frozen["fallback"]
        res = b.adopt_rows(frozen)
        assert not res["fallback"]         # B was idle: every row binds
        for rid in res["adopted"]:         # reservation travels exactly once
            a._queue.release(frozen["entries"][rid].cost)
        assert b.adopt_entries(frozen["queued"])
        for e in frozen["queued"]:
            a._queue.release(e.cost)
        a.close()
        for h in hs:
            r = h.result(timeout=120)
            assert r.status == STATUS_OK, (r.status, r.reason)
            assert r.tokens.tolist() == _ref(params, h.request.prompt,
                                             steps)
        snap = b.metrics.snapshot()
        assert snap["migrated_in"] == len(res["adopted"])
        assert snap["retries"] == 0        # nobody restarted from token 0
        assert a._queue.count == 0 and a._queue.bytes_in_flight == 0
        # the memory ledger followed the handoff: the frozen blob was
        # debited exactly once on adopt (no migration bytes linger), the
        # closed source swept everything it still owned, and B — still
        # serving — is the only engine with a resident slab
        led = memledger.get_ledger()
        mem_audit = led.audit()
        assert mem_audit["ok"], mem_audit["errors"]
        assert led.totals().get("migration", 0) == 0
        assert led.owner_bytes(a._name) == 0
        assert led.owner_bytes(b._name) > 0   # B's slab is still resident
        b.drain()
        audit = b.kvpool_audit()
        assert audit["ok"], audit["errors"]
        # drain is terminal: B's finalize swept its ledger entries too
        assert led.owner_bytes(b._name) == 0
        assert led.audit()["ok"]
    finally:
        a.close(), b.close()


def test_migration_trace_continuity_cross_process(params, tmp_path):
    """Migration-proof traces: rows frozen on A and adopted on B — with
    the live in-process span objects stripped, exactly as a cross-process
    hop would arrive — keep ONE trace_id from enqueue on A through adopt
    and result on B, and the adopt-side hop span (serve.migrate.<rid>)
    parents into the exact span the freeze manifest carried."""
    from marlin_tpu.utils.tracing import EventLog, set_default_event_log

    log = EventLog(str(tmp_path / "events.jsonl"))
    prev = set_default_event_log(log)
    a, b = _engine(params), _engine(params)
    a.warmup(), b.warmup()
    try:
        with faults.injected("serve.decode_step",
                             DelayFault(seconds=0.4, times=1,
                                        schedule=Schedule(fire_on=[2]))):
            hs = [a.submit(Request(prompt=[3, 1 + i % 4, 2], steps=8))
                  for i in range(8)]
            time.sleep(0.1)
        frozen = a.freeze_rows()
        assert frozen is not None and frozen["entries"]
        # every live row captured its submit-time span; remember it, then
        # strip the in-process objects so ONLY the manifest can carry the
        # trace across the hop (what a pickle/process boundary does)
        orig = {rid: e.trace for rid, e in frozen["entries"].items()}
        assert all(t is not None for t in orig.values())
        for e in frozen["entries"].values():
            e.trace = None
        res = b.adopt_rows(frozen)
        for rid in res["adopted"]:
            a._queue.release(frozen["entries"][rid].cost)
        assert b.adopt_entries(frozen["queued"] + res["fallback"])
        for e in frozen["queued"] + res["fallback"]:
            a._queue.release(e.cost)
        a.close()
        for h in hs:
            r = h.result(timeout=120)
            assert r.status == STATUS_OK, (r.status, r.reason)
        assert res["adopted"]  # the continuity claim needs real adoptions
        b.drain()
    finally:
        a.close(), b.close()
        set_default_event_log(prev)
        log.close()
    serve = [r for r in log.read() if r["kind"] == "serve"]
    by_rid = {}
    for rec in serve:
        if "rid" in rec and "trace_id" in rec:
            by_rid.setdefault(rec["rid"], []).append(rec)
    for rid in res["adopted"]:
        recs = by_rid.get(rid, [])
        assert recs, f"rid {rid} left no traced serve records"
        tids = {r["trace_id"] for r in recs}
        assert tids == {orig[rid].trace_id}, (rid, tids)
        evs = {r.get("ev") for r in recs}
        assert "result" in evs  # B retired it inside the SAME trace
        hops = [r for r in recs
                if r.get("ev") == "page" and r.get("action") == "adopt"]
        assert hops, f"rid {rid} adopt record missing"
        for rec in hops:
            assert rec.get("parent_id") == orig[rid].span_id, (rid, rec)


def test_adopt_rows_rejects_wrong_target(params):
    """adopt_rows on a non-running engine raises MigrationError instead of
    silently losing the frozen work (the router falls back to the retry
    path on that signal)."""
    a, b = _engine(params), _engine(params)
    a.warmup()
    try:
        with faults.injected("serve.decode_step",
                             DelayFault(seconds=0.4, times=1,
                                        schedule=Schedule(fire_on=[2]))):
            hs = [a.submit(Request(prompt=[3, 1], steps=8))
                  for _ in range(3)]
            time.sleep(0.1)               # rows live mid-decode
        frozen = a.freeze_rows()
        assert frozen["entries"]          # the raise below needs real work
        b.drain()
        with pytest.raises(MigrationError):
            b.adopt_rows(frozen)
        # the frozen work is still intact: a fresh engine can take it
        c = _engine(params)
        try:
            res = c.adopt_rows(frozen)
            for rid in res["adopted"]:
                a._queue.release(frozen["entries"][rid].cost)
            assert c.adopt_entries(frozen["queued"] + res["fallback"])
            for e in frozen["queued"] + res["fallback"]:
                a._queue.release(e.cost)
            for h in hs:
                assert h.result(timeout=120).status == STATUS_OK
        finally:
            c.close()
    finally:
        a.close(), b.close()


def test_prefix_cache_warm_transfer(params):
    """export_prefixes/import_prefixes move the hot cache entries: a
    freshly-warmed engine serves a shared-prefix prompt with cache hits
    its empty pool could never have had."""
    a, b = _engine(params), _engine(params)
    a.warmup(), b.warmup()
    shared = [7, 3, 5, 2, 6, 1, 4, 2]               # 2 full pages
    try:
        h = a.submit(Request(prompt=shared + [9, 8], steps=2))
        assert h.result(timeout=60).status == STATUS_OK
        blob = a.export_prefixes(8)
        assert blob is not None
        assert b.import_prefixes(blob) > 0
        h2 = b.submit(Request(prompt=shared + [11, 10], steps=2))
        r2 = h2.result(timeout=60)
        assert r2.status == STATUS_OK
        assert r2.tokens.tolist() == _ref(params, shared + [11, 10], 2)
        snap = b.metrics.snapshot()
        assert snap["prefix_hits"] > 0
        audit = b.kvpool_audit()
        assert audit["ok"], audit["errors"]
    finally:
        a.close(), b.close()


# ----------------------------------------------------------------- router


def test_rolling_restart_migrates_without_token0_restarts(params):
    """The rotation acceptance: a full fleet rotation under continuous
    offered load (~64 req/s) drops ZERO requests and restarts ZERO from
    token 0 — live rows migrate mid-stream (migrated_in > 0, retries ==
    0) and every output is bit-identical to the reference."""
    router = Router(_factory(params, max_batch=8, queue_depth=512,
                             num_pages=512),
                    replicas=2,
                    supervisor_kw=dict(backoff_s=0.005, poll_s=0.02),
                    rng=random.Random(7))
    handles, lock = [], threading.Lock()
    stop = threading.Event()

    def pump():
        i = 0
        while not stop.is_set():
            h = router.submit(Request(prompt=[5, 1 + i % 4], steps=4))
            with lock:
                handles.append(h)
            i += 1
            time.sleep(0.015)              # ~64 req/s

    thread = threading.Thread(target=pump)
    try:
        thread.start()
        time.sleep(0.1)
        # pin live mid-stream rows on the FIRST-rotated replica only: long
        # rows land in the (16, 8) bucket the pump never touches, and the
        # match-gated delay wedges just the worker decoding them — the
        # target replica keeps draining its own traffic at full speed, so
        # its slots are free when the frozen rows arrive (wedging both
        # workers would overload the target and force legitimate
        # fallbacks, which is the OTHER test's scenario)
        first = router._replicas[0].engine
        with faults.injected("serve.decode_step",
                             DelayFault(seconds=0.5, times=1,
                                        match="16x8")):
            with lock:
                handles.extend(first.submit(
                    Request(prompt=[2, 4, 6, 1, 3, 5, 2, 4, 6], steps=8))
                    for _ in range(4))
            time.sleep(0.05)
            rotated = router.rolling_restart()
        stop.set()
        thread.join()
        router.drain()
        assert set(rotated) == {0, 1}
        results = [h.result(timeout=120) for h in handles]
        assert len(results) >= 8
        for h, r in zip(handles, results):
            assert r.status == STATUS_OK, (r.status, r.reason)
            assert r.tokens.tolist() == _ref(params, h.request.prompt,
                                             h.request.steps)
        snap = router.snapshot()
        assert snap["migrated_in"] >= 1        # rows moved mid-stream...
        assert snap["retries"] == 0            # ...and none from token 0
        for rep in router._replicas:
            audit = rep.engine.kvpool_audit()
            assert audit["ok"], audit["errors"]
    finally:
        stop.set()
        router.close()


def test_prefix_affine_routing_concentrates(params):
    """Shared-prefix requests rendezvous onto ONE replica (whichever wins
    the hash), so its warm cache serves nearly every lookup — the bench
    acceptance's hit-parity bar, shrunk to suite scale. The very first
    request of the prefix routes load-aware (affinity engages on repeat),
    so at most one request may land off the rendezvous winner."""
    router = Router(_factory(params), replicas=2, supervise=False,
                    rng=random.Random(3))
    shared = [7, 3, 5, 2]                  # one full page: the route key
    try:
        for i in range(12):
            h = router.submit(Request(prompt=shared + [5 + i % 8, 1 + i],
                                      steps=2))
            assert h.result(timeout=60).status == STATUS_OK
        snap = router.snapshot()
        per = [s["submitted"] for s in snap["replicas"].values()]
        assert max(per) >= 11              # all but the first touch rode
        assert sorted(per)[0] <= 1         # the rendezvous winner
        assert snap["prefix_hit_rate"] >= 0.6
    finally:
        router.close()


def test_prefix_affinity_engages_only_on_repeat(params):
    """A first-page key's FIRST occurrence routes load-aware (power-of-two),
    not affine — a one-off prompt has no warm cache to win, and pinning it
    to a hash-chosen replica regardless of queue depth costs tail latency
    under unique-prompt load. The second occurrence engages rendezvous."""
    from marlin_tpu.serving.router import (_prefix_route_key,
                                           _rendezvous_score)

    router = Router(_factory(params), replicas=2, supervise=False,
                    rng=random.Random(7))
    try:
        ready = [r for r in router._replicas if r.ready()]
        # find a prompt whose rendezvous winner is replica 1, so the affine
        # order [1, 0] is distinguishable from the idle-fleet load order
        for salt in range(64):
            prompt = [salt, 3, 5, 2, 9]
            key = _prefix_route_key(Request(prompt=prompt, steps=2), ready)
            order = sorted(ready, reverse=True,
                           key=lambda r: _rendezvous_score(key, r.idx))
            if order[0].idx == 1:
                break
        else:  # pragma: no cover - blake2b would have to be pathological
            pytest.fail("no salt made replica 1 the rendezvous winner")
        req = Request(prompt=prompt, steps=2)
        first = [r.idx for r in router._candidates(req)]
        assert first == [0, 1]             # load order: both idle, idx ties
        second = [r.idx for r in router._candidates(req)]
        assert second == [1, 0]            # seen before: rendezvous order
        assert len(router._seen_prefixes) == 1
    finally:
        router.close()


def test_prefix_affinity_knob_off(params):
    """serve_prefix_affinity=False restores pure power-of-two routing —
    the knob exists precisely so a pathological prefix distribution can't
    pin a fleet to one replica with no escape hatch."""
    from marlin_tpu.config import config_context

    with config_context(serve_prefix_affinity=False):
        router = Router(_factory(params), replicas=2, supervise=False,
                        rng=random.Random(5))
        try:
            shared = [7, 3, 5, 2]
            hs = [router.submit(Request(prompt=shared + [1 + i % 8],
                                        steps=2)) for i in range(16)]
            for h in hs:
                assert h.result(timeout=60).status == STATUS_OK
            per = [s["submitted"]
                   for s in router.snapshot()["replicas"].values()]
            assert all(p > 0 for p in per)     # load spread, not pinned
        finally:
            router.close()


# ------------------------------------------------------------------ chaos


@pytest.mark.parametrize("leg", ["export:", "import@", "adopt:"])
def test_kill_mid_migration_falls_back_to_retry(params, leg):
    """A fault on any serve.migrate leg mid-rotation degrades to the PR 7
    retry path: every request still reaches exactly one ok Result
    (bit-identical — the twin restarts from token 0 by design), no page
    leaks on any replica, and the rotation itself completes."""
    memledger.reset_ledger()
    router = Router(_factory(params, max_batch=8, queue_depth=512,
                             num_pages=512),
                    replicas=2,
                    supervisor_kw=dict(backoff_s=0.005, poll_s=0.02),
                    rng=random.Random(11))
    try:
        # wedge both workers so the rotation finds live rows — the fault
        # legs under test only fire when there is real work to export
        with faults.injected("serve.decode_step",
                             DelayFault(seconds=0.5, times=2)):
            hs = [router.submit(Request(prompt=[3, 1 + i % 4], steps=8))
                  for i in range(6)]
            time.sleep(0.05)                   # rows live mid-decode
            with faults.injected("serve.migrate",
                                 RaiseFault(times=1, match=leg)):
                rotated = router.rolling_restart()
        assert set(rotated) == {0, 1}
        router.drain()
        for h in hs:
            r = h.result(timeout=120)
            assert r.status == STATUS_OK, (r.status, r.reason)
            assert r.tokens.tolist() == _ref(params, h.request.prompt, 8)
        for rep in router._replicas:
            audit = rep.engine.kvpool_audit()
            assert audit["ok"], audit["errors"]
        assert router.pending() == 0
        # ledger-audit-clean: the aborted leg's blob was swept by its
        # source's close — rotated-out engines own nothing anymore
        led = memledger.get_ledger()
        mem_audit = led.audit()
        assert mem_audit["ok"], mem_audit["errors"]
        assert led.totals().get("migration", 0) == 0
        live = {rep.engine._name for rep in router._replicas}
        for e in led.entries():
            if e["component"] in ("kvpool", "migration"):
                assert e["owner"] in live, e
    finally:
        router.close()


@pytest.mark.slow
def test_migration_chaos_soak(params):
    """Soak: repeated rotations under sustained load with a fault salted
    onto a random migration leg each round — exactly-once holds for every
    request ever accepted and every replica's pool audits clean at the
    end."""
    memledger.reset_ledger()
    rng = random.Random(0xC0FFEE)
    router = Router(_factory(params, max_batch=8, queue_depth=1024,
                             num_pages=512),
                    replicas=2,
                    supervisor_kw=dict(backoff_s=0.005, poll_s=0.02),
                    rng=random.Random(2))
    handles, lock = [], threading.Lock()
    stop = threading.Event()

    def pump():
        i = 0
        while not stop.is_set():
            h = router.submit(Request(prompt=[1 + i % 8, 3, 2],
                                      steps=2 + i % 6, max_attempts=3))
            with lock:
                handles.append(h)
            i += 1
            time.sleep(0.004)

    threads = [threading.Thread(target=pump) for _ in range(2)]
    try:
        for t in threads:
            t.start()
        for round_ in range(4):
            time.sleep(0.15)
            leg = rng.choice(["export:", "import@", "adopt:", "warm@",
                              None])
            if leg is None:
                router.rolling_restart()
            else:
                with faults.injected("serve.migrate",
                                     RaiseFault(times=1, match=leg)):
                    router.rolling_restart()
        stop.set()
        for t in threads:
            t.join()
        router.drain()
        results = [h.result(timeout=180) for h in handles]
        assert len(results) > 100
        bad = [(r.status, r.reason) for r in results
               if r.status != STATUS_OK]
        assert not bad, bad[:5]
        for h, r in zip(handles, results):
            assert r.tokens.tolist() == _ref(params, h.request.prompt,
                                             h.request.steps)
        for rep in router._replicas:
            audit = rep.engine.kvpool_audit()
            assert audit["ok"], audit["errors"]
        # after four chaos rotations the memory ledger still balances
        # exactly: no migration blob outlived its handoff, no rotated-out
        # engine left bytes behind
        led = memledger.get_ledger()
        mem_audit = led.audit()
        assert mem_audit["ok"], mem_audit["errors"]
        assert led.totals().get("migration", 0) == 0
        live = {rep.engine._name for rep in router._replicas}
        for e in led.entries():
            if e["component"] in ("kvpool", "migration"):
                assert e["owner"] in live, e
    finally:
        stop.set()
        router.close()
