"""Elastic recovery: checkpoints written on one mesh restore onto another.

The reference inherits elasticity from Spark (a lost executor's partitions are
recomputed elsewhere — SURVEY.md §5.3); the rebuild's answer is explicit
checkpoint-restart (utils/failure.py). These tests prove the *elastic* half of
that answer: state saved on an 8-device mesh resumes on 4 surviving devices,
and on a re-shaped mesh (2×4 → 4×2), via the same ``sharding=`` region-based
restore the docs advertise (io/checkpoint.py:103-118).
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import marlin_tpu as mt
from marlin_tpu.io.checkpoint import load_sharded, save_sharded
from marlin_tpu.utils.failure import ResilientLoop


def _mesh(shape, n=None):
    devs = jax.devices()[: (n or shape[0] * shape[1])]
    return mt.create_mesh(shape, devices=devs)


def test_load_sharded_onto_fewer_devices(tmp_path, mesh):
    """8-device save -> 4-device restore: the device-loss scenario."""
    a = mt.BlockMatrix.random(0, 33, 17, mesh=mesh)  # non-divisible: pads live
    save_sharded(a.data, str(tmp_path / "arr"))
    small = _mesh((2, 2))
    target = NamedSharding(small, P("rows", "cols"))
    restored = load_sharded(str(tmp_path / "arr"), sharding=target)
    np.testing.assert_array_equal(np.asarray(restored), np.asarray(a.data))
    used = {d for sh in restored.addressable_shards for d in [sh.device]}
    assert used <= set(jax.devices()[:4]), "restore touched lost devices"


def test_load_sharded_remesh(tmp_path):
    """2×4 save -> 4×2 restore: shard regions change shape entirely."""
    m24 = _mesh((2, 4))
    m42 = _mesh((4, 2))
    a = mt.BlockMatrix.random(1, 40, 24, mesh=m24)
    save_sharded(a.data, str(tmp_path / "arr"))
    restored = load_sharded(str(tmp_path / "arr"),
                            sharding=NamedSharding(m42, P("rows", "cols")))
    np.testing.assert_array_equal(np.asarray(restored), np.asarray(a.data))
    # each 4×2 shard really is a quarter-row slab, not a replicated copy
    shard_shapes = {sh.data.shape for sh in restored.addressable_shards}
    assert shard_shapes == {(10, 12)}


def test_resilient_loop_elastic_resume(tmp_path, mesh):
    """Train on 8 devices with periodic checkpoints, 'lose' half the machine,
    resume the SAME loop on a 4-device mesh template and finish: losses keep
    one entry per step and the final state lives on the survivors only."""

    def make_step(m):
        sharding = NamedSharding(m, P("rows", None))

        @jax.jit
        def step(w):
            loss = jnp.sum((w - 1.0) ** 2)
            return w - 0.1 * jax.grad(lambda v: jnp.sum((v - 1.0) ** 2))(w), loss

        def step_fn(state, i):
            new_w, loss = step(jax.device_put(state["w"], sharding))
            return {"w": new_w}, float(loss)

        return step_fn

    w0 = jnp.zeros((16, 4))
    big = mesh  # 2×4 session mesh, 8 devices
    loop1 = ResilientLoop(make_step(big), str(tmp_path), checkpoint_every=2)
    state1, metrics1 = loop1.run({"w": jax.device_put(
        w0, NamedSharding(big, P("rows", None)))}, iterations=4)
    assert len(metrics1) == 4

    # the "failure": only 4 devices survive; a fresh loop with a template
    # placed on the small mesh resumes from the step-4 checkpoint
    small = _mesh((2, 2))
    template = {"w": jax.device_put(w0, NamedSharding(small, P("rows", None)))}
    loop2 = ResilientLoop(make_step(small), str(tmp_path), checkpoint_every=2)
    state2, metrics2 = loop2.run(template, iterations=10)
    assert len(metrics2) == 6, "resume must start at the checkpointed step"
    assert metrics2[-1] < metrics1[-1], "loss must keep falling after re-mesh"
    used = {sh.device for sh in state2["w"].addressable_shards}
    assert used <= set(jax.devices()[:4])
    # the resumed trajectory equals an uninterrupted 10-step run
    ref = jnp.zeros((16, 4))
    for _ in range(10):
        ref = ref - 0.1 * 2.0 * (ref - 1.0)
    np.testing.assert_allclose(np.asarray(state2["w"]), np.asarray(ref),
                               rtol=1e-6)
