"""AOT compile-only TPU evidence (utils/aot.py): the Pallas kernels must pass
the REAL Mosaic compiler for v5e — interpret-mode correctness on the CPU mesh
(the rest of the suite) says nothing about what Mosaic accepts — and the
flash-backward memory claims must hold in the TPU lowering's own accounting,
not a CPU-lowering proxy.

These tests need libtpu (the compiler) but no chip and no relay; they skip
cleanly where libtpu is absent.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import marlin_tpu as mt
from marlin_tpu.utils.aot import supports_aot_tpu, topology_mesh, tpu_topology

pytestmark = pytest.mark.skipif(
    not supports_aot_tpu(), reason="libtpu compile-only topology unavailable")

# jax-era gate: the model-stack compiles below drive the full transformer /
# MoE / pipeline / plan_context machinery through the real TPU lowering. On
# pre-VMA jax (0.4.x) each fails minutes deep in compilation with
# era-specific errors (scan carry dtype under remat, partial-auto shard_map
# NotImplementedError, missing attributes) — the same pre-existing failure
# class as the CPU-mesh suite's shard_map tests. Skip them there so the
# doomed compiles don't dominate the tier-1 wall clock; the kernel-level
# Mosaic tests stay live on every jax era.
needs_modern_jax = pytest.mark.skipif(
    getattr(jax, "shard_map", None) is None or not hasattr(jax, "typeof"),
    reason="model-stack AOT compile needs modern jax (top-level shard_map / "
           "VMA types); fails deep in TPU lowering on jax 0.4.x")


def _one_device_sharding():
    """The canonical single-device AOT placement (replicated on one topo
    chip) — shared by every single-chip compile test."""
    from jax.sharding import Mesh

    topo = tpu_topology()
    mesh = Mesh(np.array([topo.devices[0]]).reshape(1, 1), ("a", "b"))
    return NamedSharding(mesh, P())


def _abstract_decode_args(lm):
    """Replicated abstract (sharding, params, key, temperature) for the
    decode AOT compiles — the boilerplate every decode-path test shares (a
    trace-signature change edits ONE place)."""
    rep = _one_device_sharding()
    params = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(jnp.shape(x), x.dtype, sharding=rep),
        jax.eval_shape(lm.init_params))
    key = jax.ShapeDtypeStruct((), jax.random.key(0).dtype, sharding=rep)
    temp = jax.ShapeDtypeStruct((), jnp.float32, sharding=rep)
    return rep, params, key, temp


def _compile1(fn, arg_shapes):
    """AOT-compile ``fn`` for one topology device, fully replicated."""
    rep = _one_device_sharding()
    args = [jax.ShapeDtypeStruct(s, jnp.float32) for s in arg_shapes]
    return jax.jit(fn, in_shardings=rep, out_shardings=rep) \
        .trace(*args).lower().compile()


@needs_modern_jax
def test_flash_forward_mosaic_compiles():
    from marlin_tpu.ops.flash_attention import flash_attention_panel

    S, D, B = 2048, 128, 1024
    c = _compile1(
        lambda q, k, v, m, l, acc: flash_attention_panel(
            q, k, v, m, l, acc, 0, 0, S, causal=True, scale=0.125,
            bq=B, bkv=B, interpret=False),
        [(S, D), (S, D), (S, D), (S,), (S,), (S, D)])
    assert c.memory_analysis().temp_size_in_bytes == 0  # streams via VMEM


def test_flash_backward_mosaic_compiles():
    from marlin_tpu.ops.flash_attention import flash_attention_panel_bwd

    S, D, B = 2048, 128, 1024
    c = _compile1(
        lambda q, k, v, do, lse, delta: flash_attention_panel_bwd(
            q, k, v, do, lse, delta, 0, 0, S, causal=True, scale=0.125,
            bq=B, bkv=B, interpret=False),
        [(S, D), (S, D), (S, D), (S, D), (S,), (S,)])
    assert c.memory_analysis().temp_size_in_bytes == 0


def test_bsr_manual_dma_mosaic_compiles():
    """The double-buffered make_async_copy kernel with pl.ANY HBM refs and
    scalar-prefetch-driven index maps — exactly the shape of code Mosaic
    rejects in surprising ways (round-2/3 verdicts); prove it compiles."""
    from marlin_tpu.ops.sparse_bsr import BsrMatrix, bsr_from_coo, \
        bsr_spmm_pallas

    rng = np.random.default_rng(0)
    M = N = K = 1024
    bs, nb = 128, 12
    flat = rng.choice(M // bs * (K // bs), nb, replace=False)
    ri, ci = np.divmod(flat, K // bs)
    coo_r = np.concatenate([(r * bs + np.arange(bs)).repeat(bs) for r in ri])
    coo_c = np.concatenate([np.tile(c * bs + np.arange(bs), bs) for c in ci])
    coo_v = rng.random(nb * bs * bs).astype(np.float32)
    bsr = bsr_from_coo(coo_r, coo_c, coo_v, (M, K), block_size=bs)

    def spmm(blocks, b):
        m = BsrMatrix(blocks=blocks, block_rows=bsr.block_rows,
                      block_cols=bsr.block_cols, shape=bsr.shape,
                      block_size=bsr.block_size)
        return bsr_spmm_pallas(m, b, interpret=False)

    _compile1(spmm, [tuple(bsr.blocks.shape), (K, N)])


def _ring_grad_memory(seq, backend):
    from marlin_tpu.parallel.ring_attention import ring_attention

    mesh = topology_mesh(("rows",), (4,))
    s = NamedSharding(mesh, P("rows", None))
    with mt.config_context(pallas_interpret=False):
        g = jax.jit(
            jax.grad(lambda q, k, v: jnp.sum(ring_attention(
                q, k, v, mesh, causal=True, backend=backend)),
                argnums=(0, 1, 2)),
            in_shardings=(s, s, s), out_shardings=(s, s, s))
        a = jax.ShapeDtypeStruct((seq, 128), jnp.float32)
        return g.trace(a, a, a).lower().compile().memory_analysis()


@needs_modern_jax
def test_flash_backward_memory_flat_on_tpu():
    """TPU-lowering accounting of the training backward (the CPU-proxy
    version lives in test_ring_attention.py): the flash path holds ZERO HBM
    temps at any length — score tiles live and die in VMEM — and its peak
    memory is linear in seq; the autodiff-through-XLA backward it replaced
    pays quadratic-plus temp growth at the same shapes."""
    f8, f16 = _ring_grad_memory(8192, "flash"), _ring_grad_memory(16384, "flash")
    assert f8.temp_size_in_bytes == 0 and f16.temp_size_in_bytes == 0
    assert f16.peak_memory_in_bytes < 2.5 * f8.peak_memory_in_bytes

    x16 = _ring_grad_memory(16384, "xla")
    # the replaced formulation's residuals: ~830 MB of temps at 16k vs 0
    assert x16.temp_size_in_bytes > 100 * 1024 * 1024
    assert x16.peak_memory_in_bytes > 10 * f16.peak_memory_in_bytes


@needs_modern_jax
def test_distributed_engines_compile_for_8chip_v5e():
    """The flagship distributed programs — gspmd, ring (ppermute pipeline),
    3-D RMM (psum over k), ulysses (all_to_all re-shard) — AOT-compiled for
    a real 8-chip v5e topology: the collective schedules the CPU mesh proves
    numerically are accepted and scheduled by the TPU compiler over ICI."""
    from jax.sharding import Mesh

    from marlin_tpu.parallel.matmul import gspmd_matmul, rmm_matmul
    from marlin_tpu.parallel.ring import ring_matmul
    from marlin_tpu.parallel.ulysses import ulysses_attention

    topo = tpu_topology("v5e:2x4")
    devs = list(np.asarray(topo.devices).ravel())
    mesh2d = Mesh(np.array(devs).reshape(2, 4), ("rows", "cols"))
    row = NamedSharding(mesh2d, P("rows", None))
    blk = NamedSharding(mesh2d, P("rows", "cols"))
    a = jax.ShapeDtypeStruct((512, 512), jnp.float32, sharding=row)

    c = jax.jit(lambda x, y: gspmd_matmul(x, y, blk)) \
        .trace(a, a).lower().compile()
    assert c.memory_analysis().peak_memory_in_bytes > 0

    jax.jit(lambda x, y: ring_matmul(x, y, mesh2d)) \
        .trace(a, a).lower().compile()

    jax.jit(lambda x, y: rmm_matmul(x, y, split=(2, 2, 2), devices=devs)) \
        .trace(a, a).lower().compile()

    meshr = Mesh(np.array(devs).reshape(8), ("rows",))
    h = jax.ShapeDtypeStruct((8, 1024, 128), jnp.float32,
                             sharding=NamedSharding(meshr, P(None, "rows", None)))
    with mt.config_context(pallas_interpret=False):
        jax.jit(lambda q, k, v: ulysses_attention(q, k, v, meshr, causal=True)) \
            .trace(h, h, h).lower().compile()


@needs_modern_jax
def test_decode_path_compiles_for_v5e():
    """lm_generate (batched prefill + scan decode + traced temperature)
    AOT-compiled for a v5e device — the decode bench's program is proven
    before it ever reaches the chip."""
    from marlin_tpu.models.transformer import (TransformerLM,
                                               _lm_generate_batch_jit,
                                               _lm_generate_jit)

    lm = TransformerLM(vocab=4096, d_model=512, heads=8, layers=4, seed=0)
    rep, params, key, temp = _abstract_decode_args(lm)
    prompt = jax.ShapeDtypeStruct((512,), jnp.int32, sharding=rep)
    c = _lm_generate_jit.trace(params, prompt, key, heads=8, max_len=832,
                               steps=320, temperature=temp,
                               compute_dtype=None, top_p=temp,
                               use_top_p=True, top_k=40).lower().compile()
    assert c.memory_analysis().peak_memory_in_bytes < 2 * 1024**3

    # the batched serving form: 8 ragged rows decode together
    prompts = jax.ShapeDtypeStruct((8, 512), jnp.int32, sharding=rep)
    lengths = jax.ShapeDtypeStruct((8,), jnp.int32, sharding=rep)
    cb = _lm_generate_batch_jit.trace(
        params, prompts, lengths, key, heads=8, max_len=576, steps=64,
        temperature=temp, compute_dtype=None, top_p=temp, use_top_p=True,
        top_k=40).lower().compile()
    assert cb.memory_analysis().peak_memory_in_bytes < 4 * 1024**3


def test_pallas_matmul_and_masked_fill_mosaic_compile():
    """The remaining two Pallas kernels (tiled MXU matmul, fused pad-mask)
    through real Mosaic — completing 'every Pallas kernel is AOT-proven'."""
    from marlin_tpu.ops.pallas_kernels import masked_fill, pallas_matmul

    rep = _one_device_sharding()
    with mt.config_context(pallas_interpret=False):
        a = jax.ShapeDtypeStruct((512, 384), jnp.float32)
        b = jax.ShapeDtypeStruct((384, 256), jnp.float32)
        jax.jit(lambda a, b: pallas_matmul(a, b), in_shardings=rep,
                out_shardings=rep).trace(a, b).lower().compile()
        x = jax.ShapeDtypeStruct((256, 256), jnp.float32)
        jax.jit(lambda x: masked_fill(x, 200, 190), in_shardings=rep,
                out_shardings=rep).trace(x).lower().compile()


@needs_modern_jax
def test_flash_prefill_memory_linear_on_tpu():
    """Decode prefill past _PREFILL_FLASH_MIN runs the flash kernel, so the
    prompt's score memory never materializes: TPU-compiler peak for the whole
    lm_generate program must grow ~linearly from 8k to 16k prompts (the dense
    path it replaced held heads x P² f32 scores per layer — 2.1 -> 8.6 GiB
    quadratic growth at these shapes; ADVICE r4 / round-4 verdict #3)."""
    from marlin_tpu.models.transformer import (TransformerLM,
                                               _lm_generate_jit)

    lm = TransformerLM(vocab=4096, d_model=512, heads=8, layers=4, seed=0)
    rep, params, key, temp = _abstract_decode_args(lm)

    def peak(plen):
        prompt = jax.ShapeDtypeStruct((plen,), jnp.int32, sharding=rep)
        with mt.config_context(pallas_interpret=False):
            c = _lm_generate_jit.trace(
                params, prompt, key, heads=8, max_len=plen + 16, steps=16,
                temperature=temp, compute_dtype=None, top_p=temp,
                use_top_p=False, top_k=None).lower().compile()
        return c.memory_analysis().peak_memory_in_bytes

    p8, p16 = peak(8192), peak(16384)
    assert p16 < 2.6 * p8, (p8, p16)
    # and nowhere near the dense path's 8.6 GiB of scores
    assert p16 < 2 * 1024**3, p16


@needs_modern_jax
def test_plan_context_real_compiles():
    """plan_context against the real compiler: a tiny model at 32k tokens
    fits a generous budget as-configured, and a deliberately starved budget
    forces knob escalation whose chosen rung really fits (every number here
    is the TPU compiler's own accounting, not a heuristic)."""
    from marlin_tpu.models import TransformerLM, plan_context

    lm = TransformerLM(vocab=256, d_model=64, heads=2, layers=2,
                       attn="ring_flash")
    seq = 32768
    generous = plan_context(seq, lm, hbm_budget=15 * 1024**3)
    assert generous.fits and generous.knobs == {}

    starved = plan_context(seq, lm, hbm_budget=generous.peak_bytes - 1)
    assert starved.fits, starved.describe()
    assert starved.knobs  # at least one knob escalated
    assert starved.peak_bytes < generous.peak_bytes


@needs_modern_jax
def test_2m_tokens_single_chip_and_host_offload():
    """The single-chip context cliff (r4 verdict #5), compiler-verified:

    1. 2M bf16 tokens — a 17-GiB compiler REJECTION before the exact-packed
       m/l kernel layout — now fit one v5e under *usable* HBM with the
       on-device knobs alone (remat + loss_chunk + mlp_chunk + bf16).
    2. offload_residuals genuinely moves the remat checkpoints off the
       device: ~2 GiB of host temps appear in the compiler's host-memory
       accounting and the device program still compiles. (At THIS config it
       is net-neutral — the scan formulation costs about what the offload
       saves — so it is the knob for residual-dominated shapes, more
       layers x d_model, not the default.)"""
    from marlin_tpu.models.planner import _compiled_peak, usable_hbm_bytes
    from marlin_tpu.models.transformer import TransformerLM

    mesh = topology_mesh(("rows",), (1,))
    lm = TransformerLM(vocab=512, d_model=256, heads=2, layers=2,
                       attn="ring_flash", remat=True, loss_chunk=16384,
                       compute_dtype="bfloat16", mlp_chunk=16384)
    peak, note = _compiled_peak(lm, 2097152, mesh)
    assert peak is not None, note
    assert peak <= usable_hbm_bytes(), (peak, usable_hbm_bytes())

    import dataclasses

    from marlin_tpu.utils.aot import trace_lm_train_step

    lm_off = dataclasses.replace(lm, offload_residuals=True)
    with mt.config_context(pallas_interpret=False):
        c = trace_lm_train_step(lm_off, 2097152, mesh).lower().compile()
    ma = c.memory_analysis()
    # the residuals (2 layers x 2M x 256 x bf16 = 2 GiB) live on the host
    assert ma.host_temp_size_in_bytes >= 2 * 1024**3
    assert ma.peak_memory_in_bytes < 16 * 1024**3


@needs_modern_jax
def test_plan_context_multichip():
    """chips=4 certifies the SAME sharded ring program per chip: the 4M-token
    bf16 deployment the docs claim (remat + loss_chunk + bf16, AOT_MEMORY's
    lct_long_4chip row — NOT mlp_chunk, which measures ~1 GiB WORSE per chip
    in the sharded program; nonmonotonic knob interactions across topologies
    are exactly why the planner measures instead of assuming) compiles within
    per-chip usable HBM."""
    from marlin_tpu.models import TransformerLM, plan_context

    lm = TransformerLM(vocab=512, d_model=256, heads=2, layers=2,
                       attn="ring_flash", remat=True, loss_chunk=16384,
                       compute_dtype="bfloat16")
    plan = plan_context(4 * 1048576, lm, chips=4)
    assert plan.fits, plan.describe()
    assert plan.knobs == {}, plan.knobs  # fits as-documented, no escalation


@needs_modern_jax
def test_batched_long_prompt_decode_compiles():
    """lm_generate_batch with prompts past _PREFILL_FLASH_MIN: the flash
    prefill kernel under NESTED vmap (batch x heads) must fold into the
    Mosaic grid and compile — the long-document serving shape."""
    from marlin_tpu.models.transformer import (TransformerLM,
                                               _lm_generate_batch_jit)

    lm = TransformerLM(vocab=4096, d_model=512, heads=8, layers=4, seed=0)
    rep, params, key, temp = _abstract_decode_args(lm)
    prompts = jax.ShapeDtypeStruct((4, 4096), jnp.int32, sharding=rep)
    lengths = jax.ShapeDtypeStruct((4,), jnp.int32, sharding=rep)
    with mt.config_context(pallas_interpret=False):
        c = _lm_generate_batch_jit.trace(
            params, prompts, lengths, key, heads=8, max_len=4160, steps=64,
            temperature=temp, compute_dtype=None, top_p=temp,
            use_top_p=False, top_k=None).lower().compile()
    assert c.memory_analysis().peak_memory_in_bytes < 2 * 1024**3


@needs_modern_jax
def test_gqa_decode_compiles_for_v5e():
    """The grouped-query decode program (kv_heads=2 of 8: grouped einsums,
    quarter-width caches) compiles for v5e and its peak sits measurably
    below the full-MHA decode program at the same shape — the cache
    reduction is visible in the compiler's own accounting."""
    from marlin_tpu.models.transformer import TransformerLM, _lm_generate_jit

    def peak(kvh):
        lm = TransformerLM(vocab=4096, d_model=512, heads=8, layers=4,
                           seed=0, kv_heads=kvh)
        rep, params, key, temp = _abstract_decode_args(lm)
        prompt = jax.ShapeDtypeStruct((512,), jnp.int32, sharding=rep)
        c = _lm_generate_jit.trace(
            params, prompt, key, heads=8, max_len=8192, steps=64,
            temperature=temp, compute_dtype=None, top_p=temp,
            use_top_p=False, top_k=None).lower().compile()
        return c.memory_analysis().peak_memory_in_bytes

    full, grouped = peak(None), peak(2)
    assert grouped < full, (grouped, full)
    # caches: 4 layers x 2 tensors x 8192 x 8 heads x dh=64 x f32 = 128 MB
    # total at full width; kv_heads=2 keeps a quarter -> ~96 MB reclaimed
    # (measured 102 MB of a 227 MB full-decode peak)
    assert full - grouped > 90 * 1024 * 1024, (grouped, full)


@needs_modern_jax
def test_moe_train_step_compiles_for_v5e():
    """The MoE LM train step (grouped GShard routing + Switch aux in the
    loss) through the REAL TPU compiler, single chip — top_k/cumsum/one_hot
    dispatch einsums and the scan-over-groups must all lower."""
    from marlin_tpu.models import TransformerLM
    from marlin_tpu.utils.aot import trace_lm_train_step

    mesh = topology_mesh(("rows", "cols"), (1, 1))
    lm = TransformerLM(vocab=512, d_model=256, heads=2, layers=2, remat=True,
                       loss_chunk=2048, n_experts=8, moe_group=2048)
    with mt.config_context(pallas_interpret=False):
        c = trace_lm_train_step(lm, 32768, mesh).lower().compile()
    peak = c.memory_analysis().peak_memory_in_bytes
    assert 0 < peak < 16 * 1024 ** 3, peak


@needs_modern_jax
def test_moe_expert_parallel_compiles_for_4chip_v5e():
    """Expert parallelism for a real 4-chip v5e: expert params sharded over
    the rows axis (the placement idiom), the compiler must accept and
    schedule the token-shuffle collectives its propagation inserts."""
    from marlin_tpu.models.moe import init_moe, moe_ffn

    mesh = topology_mesh(("rows", "cols"), (4, 1))
    mp = jax.eval_shape(lambda: init_moe(jax.random.key(0), 256, 1024, 8))
    exp = NamedSharding(mesh, P("rows", None, None))
    rep = NamedSharding(mesh, P())
    mp = {
        "wg": jax.ShapeDtypeStruct(mp["wg"].shape, mp["wg"].dtype,
                                   sharding=rep),
        "w1": jax.ShapeDtypeStruct(mp["w1"].shape, mp["w1"].dtype,
                                   sharding=exp),
        "w2": jax.ShapeDtypeStruct(mp["w2"].shape, mp["w2"].dtype,
                                   sharding=exp),
    }
    x = jax.ShapeDtypeStruct((16384, 256), jnp.float32,
                             sharding=NamedSharding(mesh, P("rows", None)))
    c = jax.jit(lambda m, xx: moe_ffn(m, xx, mesh=mesh, top_k=2,
                                      group_size=4096)) \
        .trace(mp, x).lower().compile()
    assert c.memory_analysis().peak_memory_in_bytes > 0


@needs_modern_jax
def test_pipeline_compiles_for_4chip_v5e():
    """The GPipe schedule (shard_map + ppermute hops + masked psum collect)
    through the TPU compiler for a real 4-chip topology."""
    from marlin_tpu.parallel.pipeline import pipeline_apply

    mesh = topology_mesh(("rows", "cols"), (4, 1))
    stage = NamedSharding(mesh, P("rows", None, None))
    params = {"w": jax.ShapeDtypeStruct((4, 512, 512), jnp.float32,
                                        sharding=stage)}
    x = jax.ShapeDtypeStruct((64, 512), jnp.float32,
                             sharding=NamedSharding(mesh, P()))
    c = jax.jit(lambda p, xx: pipeline_apply(
        p, lambda ps, xb: jnp.tanh(xb @ ps["w"]), xx, mesh, microbatch=8)) \
        .trace(params, x).lower().compile()
    assert c.memory_analysis().peak_memory_in_bytes > 0


@needs_modern_jax
def test_plan_context_moe_model():
    """The planner handles MoE models end-to-end: the traced step carries
    the routing + aux and the expert tensors get their runtime EP sharding,
    so the compiler accounting the plan is built from matches the deployed
    program."""
    from marlin_tpu.models import TransformerLM, plan_context

    lm = TransformerLM(vocab=256, d_model=64, heads=2, layers=2,
                       attn="ring_flash", n_experts=4, moe_group=2048)
    plan = plan_context(16384, lm, hbm_budget=15 * 1024 ** 3)
    assert plan.fits and plan.peak_bytes > 0


@needs_modern_jax
def test_pipeline_tensor_parallel_composition_compiles():
    """pp x tp on one mesh: pipeline stages over "rows" whose stage_fn is
    itself tensor-parallel over "cols" (column-sharded w0, row-sharded w1;
    pipeline_apply manualizes only the pipeline axis, so "cols" stays Auto
    and GSPMD shards the stage matmuls). Certified two ways: the TPU
    compiler accepts the composed program, AND the per-device argument
    footprint of the cols-sharded weights is ~half the replicated compile's
    — the tensor sharding genuinely survives into the pipeline (a
    fully-manual shard_map would all-gather it away at the boundary)."""
    from marlin_tpu.parallel.pipeline import pipeline_apply

    mesh = topology_mesh(("rows", "cols"), (2, 2))
    stage = NamedSharding(mesh, P("rows", None, None))
    col = NamedSharding(mesh, P("rows", None, "cols"))
    roww = NamedSharding(mesh, P("rows", "cols", None))
    x = jax.ShapeDtypeStruct((16, 256), jnp.float32,
                             sharding=NamedSharding(mesh, P()))

    def stage_fn(p, xb):
        h = jax.nn.relu(xb @ p["w0"])
        return jnp.tanh(h @ p["w1"] + p["b"])

    def compiled(w0_sh, w1_sh):
        params = {
            "w0": jax.ShapeDtypeStruct((2, 256, 512), jnp.float32,
                                       sharding=w0_sh),
            "w1": jax.ShapeDtypeStruct((2, 512, 256), jnp.float32,
                                       sharding=w1_sh),
            "b": jax.ShapeDtypeStruct(
                (2, 256), jnp.float32,
                sharding=NamedSharding(mesh, P("rows", None))),
        }
        return jax.jit(lambda p, xx: pipeline_apply(
            p, stage_fn, xx, mesh, microbatch=4)) \
            .trace(params, x).lower().compile()

    tp = compiled(col, roww).memory_analysis()
    rep = compiled(stage, stage).memory_analysis()
    assert tp.peak_memory_in_bytes > 0
    assert tp.argument_size_in_bytes < 0.75 * rep.argument_size_in_bytes, (
        tp.argument_size_in_bytes, rep.argument_size_in_bytes)


@needs_modern_jax
def test_pp_lm_train_step_compiles_for_4chip_v5e():
    """The pipeline-parallel LM train step (4 stages of 1 block each,
    batched causal attention inside stages, Adam over stage + outer params)
    through the TPU compiler for a real 4-chip topology."""
    import optax

    from marlin_tpu.models.pipeline_lm import pp_lm_train_step, pp_stage_params
    from marlin_tpu.models.transformer import init_transformer

    mesh = topology_mesh(("rows", "cols"), (4, 1))
    params = jax.eval_shape(
        lambda: init_transformer(jax.random.key(0), 256, 128, 2, 4))
    rep = NamedSharding(mesh, P())

    def absify(tree, shardings):
        return jax.tree.map(
            lambda x, s: jax.ShapeDtypeStruct(jnp.shape(x), x.dtype,
                                              sharding=s), tree, shardings)

    # build the REAL stage split abstractly: eval_shape pp_stage_params to
    # get shapes, then shard the stage axis like the runtime does
    sp_shape, outer_shape = jax.eval_shape(
        lambda p: pp_stage_params(p, mesh), params)
    stage_sh = jax.tree.map(
        lambda x: NamedSharding(mesh, P("rows", *(None,) * (x.ndim - 1))),
        sp_shape)
    sp = absify(sp_shape, stage_sh)
    outer = absify(outer_shape, jax.tree.map(lambda _: rep, outer_shape))
    opt_shape = jax.eval_shape(optax.adam(1e-3).init, (sp_shape, outer_shape))
    opt = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(jnp.shape(x), x.dtype, sharding=rep),
        opt_shape)
    toks = jax.ShapeDtypeStruct((8, 129), jnp.int32, sharding=rep)
    with mt.config_context(pallas_interpret=False):
        c = pp_lm_train_step.trace(sp, outer, opt, toks, mesh, heads=2,
                                   microbatch=2, lr=1e-3).lower().compile()
    assert c.memory_analysis().peak_memory_in_bytes > 0
