"""GPipe pipeline parallelism: schedule correctness and differentiability.

The oracle is the plain sequential composition of the stages — the pipeline
is purely an execution schedule, so its output (and gradients) must match
bit-for-bit-level tolerances on the CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import marlin_tpu as mt
from marlin_tpu.parallel.pipeline import (pipeline_apply, split_microbatches,
                                          stack_stage_params)

import jax as _jax_mod

# jax-0.4.37-era gate: these cases exercise behaviour that only works in
# the top-level jax.shard_map / jax.typeof era (partial-auto shard_map,
# scan-carry replication checks) -- same class as tests/test_aot_tpu.py.
needs_modern_jax = pytest.mark.skipif(
    getattr(_jax_mod, "shard_map", None) is None
    or not hasattr(_jax_mod, "typeof"),
    reason="needs modern jax (top-level shard_map / typeof era)")



def _mlp_stage(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def _make_stages(key, n_stages, d):
    ks = jax.random.split(key, n_stages)
    return [
        {"w": jax.random.normal(k, (d, d), jnp.float32) / np.sqrt(d),
         "b": jnp.zeros((d,), jnp.float32)}
        for k in ks
    ]


def _sequential(per_stage, x):
    for p in per_stage:
        x = _mlp_stage(p, x)
    return x


@pytest.fixture
def mesh4():
    return mt.create_mesh((4, 2))


@needs_modern_jax
def test_pipeline_matches_sequential(mesh4):
    rng = np.random.default_rng(0)
    d, batch = 16, 24
    per_stage = _make_stages(jax.random.key(1), 4, d)
    x = jnp.asarray(rng.standard_normal((batch, d)).astype(np.float32))
    stacked = stack_stage_params(per_stage, mesh4)
    out = pipeline_apply(stacked, _mlp_stage, x, mesh4, microbatch=4)
    ref = _sequential(per_stage, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


@needs_modern_jax
def test_pipeline_default_microbatch(mesh4):
    # default microbatch = batch // n_stages: still exact
    rng = np.random.default_rng(1)
    d, batch = 8, 8
    per_stage = _make_stages(jax.random.key(2), 4, d)
    x = jnp.asarray(rng.standard_normal((batch, d)).astype(np.float32))
    out = pipeline_apply(stack_stage_params(per_stage, mesh4), _mlp_stage, x,
                         mesh4)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_sequential(per_stage, x)),
                               rtol=1e-5, atol=1e-6)


@needs_modern_jax
def test_pipeline_single_microbatch_many(mesh4):
    # M > S and M = batch (microbatch=1): deepest schedule, still exact
    rng = np.random.default_rng(2)
    d, batch = 8, 6
    per_stage = _make_stages(jax.random.key(3), 4, d)
    x = jnp.asarray(rng.standard_normal((batch, d)).astype(np.float32))
    out = pipeline_apply(stack_stage_params(per_stage, mesh4), _mlp_stage, x,
                         mesh4, microbatch=1)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_sequential(per_stage, x)),
                               rtol=1e-5, atol=1e-6)


@needs_modern_jax
def test_pipeline_grad_matches_sequential(mesh4):
    rng = np.random.default_rng(3)
    d, batch = 8, 16
    per_stage = _make_stages(jax.random.key(4), 4, d)
    x = jnp.asarray(rng.standard_normal((batch, d)).astype(np.float32))
    y = jnp.asarray(rng.standard_normal((batch, d)).astype(np.float32))
    stacked = stack_stage_params(per_stage, mesh4)

    def pipe_loss(params):
        out = pipeline_apply(params, _mlp_stage, x, mesh4, microbatch=4)
        return jnp.mean((out - y) ** 2)

    def seq_loss(per_stage_list):
        out = x
        for p in per_stage_list:
            out = _mlp_stage(p, out)
        return jnp.mean((out - y) ** 2)

    g_pipe = jax.grad(pipe_loss)(stacked)
    g_seq = jax.grad(seq_loss)(per_stage)
    for s in range(4):
        np.testing.assert_allclose(np.asarray(g_pipe["w"][s]),
                                   np.asarray(g_seq[s]["w"]),
                                   rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(np.asarray(g_pipe["b"][s]),
                                   np.asarray(g_seq[s]["b"]),
                                   rtol=1e-4, atol=1e-6)


@needs_modern_jax
def test_pipeline_jit_train_step(mesh4):
    # one jitted SGD step through the pipeline drops the loss
    rng = np.random.default_rng(4)
    d, batch = 8, 16
    per_stage = _make_stages(jax.random.key(5), 4, d)
    x = jnp.asarray(rng.standard_normal((batch, d)).astype(np.float32))
    y = jnp.asarray(rng.standard_normal((batch, d)).astype(np.float32) * 0.1)
    params = stack_stage_params(per_stage, mesh4)

    @jax.jit
    def step(params):
        def loss(p):
            out = pipeline_apply(p, _mlp_stage, x, mesh4, microbatch=4)
            return jnp.mean((out - y) ** 2)

        l, g = jax.value_and_grad(loss)(params)
        return jax.tree.map(lambda w, gw: w - 0.1 * gw, params, g), l

    p1, l0 = step(params)
    _, l1 = step(p1)
    assert float(l1) < float(l0)


def test_pipeline_validation(mesh4):
    per_stage = _make_stages(jax.random.key(6), 3, 8)  # wrong count
    with pytest.raises(ValueError, match="3 stage param sets"):
        stack_stage_params(per_stage, mesh4)
    with pytest.raises(ValueError, match="multiple of microbatch"):
        split_microbatches(jnp.zeros((10, 4)), 3)


@needs_modern_jax
def test_pipeline_tensor_parallel_stage_matches_sequential(mesh4):
    # pp x tp numerically: stage weights additionally sharded over "cols"
    # (column-split w0, row-split w1 — XLA's activation psum runs inside the
    # pipeline's Manual-rows context); output must still equal the plain
    # sequential composition
    from jax.sharding import NamedSharding, PartitionSpec as P

    rng = np.random.default_rng(7)
    d, ff, batch = 8, 16, 16
    per_stage = [
        {"w0": jnp.asarray(rng.standard_normal((d, ff)).astype(np.float32)) / 3,
         "w1": jnp.asarray(rng.standard_normal((ff, d)).astype(np.float32)) / 4}
        for _ in range(4)
    ]
    x = jnp.asarray(rng.standard_normal((batch, d)).astype(np.float32))

    def fn(p, xb):
        return jnp.tanh(jax.nn.relu(xb @ p["w0"]) @ p["w1"])

    stacked = stack_stage_params(per_stage, mesh4)
    stacked = {
        "w0": jax.device_put(stacked["w0"],
                             NamedSharding(mesh4, P("rows", None, "cols"))),
        "w1": jax.device_put(stacked["w1"],
                             NamedSharding(mesh4, P("rows", "cols", None))),
    }
    out = jax.jit(lambda p, xx: pipeline_apply(p, fn, xx, mesh4,
                                               microbatch=4))(stacked, x)
    ref = x
    for p in per_stage:
        ref = fn(p, ref)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)
