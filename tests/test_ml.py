"""ML workload tests: ALS (untested in the reference — SURVEY.md §4), plus the
CARMA split heuristic properties."""

import numpy as np
import pytest

import marlin_tpu as mt
from marlin_tpu.parallel.carma import near_square_split, split_method


def test_als_reduces_rmse(mesh):
    rng = np.random.default_rng(0)
    n_users, n_items, rank = 30, 20, 4
    u_true = rng.standard_normal((n_users, rank)).astype(np.float32)
    v_true = rng.standard_normal((n_items, rank)).astype(np.float32)
    full = u_true @ v_true.T
    # observe 50% of entries
    mask = rng.random((n_users, n_items)) < 0.5
    ui, ii = np.nonzero(mask)
    coo = mt.CoordinateMatrix(ui, ii, full[mask], shape=(n_users, n_items), mesh=mesh)
    model = coo.als(rank=rank, iterations=12, lam=0.05)
    rmse = model.rmse(coo)
    assert rmse < 0.3, f"ALS failed to fit: rmse={rmse}"
    assert model.user_features.shape == (n_users, rank)
    assert model.product_features.shape == (n_items, rank)


def test_als_predict_shape(mesh):
    coo = mt.CoordinateMatrix.from_entries(
        [(0, 0, 5.0), (0, 1, 3.0), (1, 0, 4.0), (2, 1, 1.0)], mesh=mesh
    )
    model = coo.als(rank=2, iterations=5, lam=0.1)
    preds = model.predict([0, 1], [0, 0])
    assert preds.shape == (2,)


def test_carma_split_budget():
    for m, k, n, p in [(100, 100, 100, 8), (10000, 100, 100, 8), (64, 4096, 64, 16)]:
        ms, ks, ns = split_method(m, k, n, p)
        assert ms * ks * ns <= p
        assert ms >= 1 and ks >= 1 and ns >= 1


def test_carma_prefers_long_dim():
    # k is dominant -> k gets the splits (contraction-parallel, psum over k)
    ms, ks, ns = split_method(64, 65536, 64, 8)
    assert ks == 8 and ms == 1 and ns == 1
    # m dominant -> row-parallel, collective-free
    ms, ks, ns = split_method(65536, 64, 64, 8)
    assert ms == 8


def test_near_square_split():
    assert near_square_split(9) == 3
    assert near_square_split(1) >= 1


def test_als_implicit_prefs(mesh):
    # implicit feedback: observed entries should score higher than unobserved
    rng = np.random.default_rng(1)
    n_users, n_items = 25, 15
    mask = rng.random((n_users, n_items)) < 0.3
    ui, ii = np.nonzero(mask)
    counts = rng.integers(1, 10, len(ui)).astype(np.float32)  # interaction counts
    coo = mt.CoordinateMatrix(ui, ii, counts, shape=(n_users, n_items), mesh=mesh)
    model = coo.als(rank=4, iterations=10, lam=0.1, implicit_prefs=True, alpha=10.0)
    u = model.user_features.to_numpy()
    v = model.product_features.to_numpy()
    scores = u @ v.T
    obs_mean = scores[mask].mean()
    unobs_mean = scores[~mask].mean()
    assert obs_mean > unobs_mean + 0.1, (obs_mean, unobs_mean)
