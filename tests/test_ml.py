"""ML workload tests: ALS (untested in the reference — SURVEY.md §4), plus the
CARMA split heuristic properties."""

import os

import numpy as np
import pytest

import marlin_tpu as mt
from marlin_tpu.parallel.carma import near_square_split, split_method


def test_als_reduces_rmse(mesh):
    rng = np.random.default_rng(0)
    n_users, n_items, rank = 30, 20, 4
    u_true = rng.standard_normal((n_users, rank)).astype(np.float32)
    v_true = rng.standard_normal((n_items, rank)).astype(np.float32)
    full = u_true @ v_true.T
    # observe 50% of entries
    mask = rng.random((n_users, n_items)) < 0.5
    ui, ii = np.nonzero(mask)
    coo = mt.CoordinateMatrix(ui, ii, full[mask], shape=(n_users, n_items), mesh=mesh)
    model = coo.als(rank=rank, iterations=12, lam=0.05)
    rmse = model.rmse(coo)
    assert rmse < 0.3, f"ALS failed to fit: rmse={rmse}"
    assert model.user_features.shape == (n_users, rank)
    assert model.product_features.shape == (n_items, rank)


def test_als_predict_shape(mesh):
    coo = mt.CoordinateMatrix.from_entries(
        [(0, 0, 5.0), (0, 1, 3.0), (1, 0, 4.0), (2, 1, 1.0)], mesh=mesh
    )
    model = coo.als(rank=2, iterations=5, lam=0.1)
    preds = model.predict([0, 1], [0, 0])
    assert preds.shape == (2,)


def _rating_fixture(seed, n_users, n_items, rank, density, mesh):
    rng = np.random.default_rng(seed)
    u_true = rng.standard_normal((n_users, rank)).astype(np.float32)
    v_true = rng.standard_normal((n_items, rank)).astype(np.float32)
    full = u_true @ v_true.T
    mask = rng.random((n_users, n_items)) < density
    ui, ii = np.nonzero(mask)
    return mt.CoordinateMatrix(ui, ii, full[mask], shape=(n_users, n_items),
                               mesh=mesh)


def test_als_sharded_matches_replicated(mesh):
    # same init, same data: the mesh-sharded solver must agree with the
    # replicated one up to FP summation order
    coo = _rating_fixture(2, 60, 40, 4, 0.5, mesh)
    rep = coo.als(rank=4, iterations=6, lam=0.05, shard=False)
    sh = coo.als(rank=4, iterations=6, lam=0.05, shard=True, segment_block=8)
    np.testing.assert_allclose(sh.user_features.to_numpy(),
                               rep.user_features.to_numpy(),
                               rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(sh.product_features.to_numpy(),
                               rep.product_features.to_numpy(),
                               rtol=2e-3, atol=2e-3)
    assert sh.rmse(coo) < 0.3


def test_als_blocked_single_device(mesh):
    # shard=True on a ONE-device mesh is the bounded-memory blocked mode (the
    # single-chip ALS bench config OOMs through the unsharded path: 31 GB of
    # (num_users, rank, rank) stats vs 16 GB HBM); it must agree with the
    # unsharded solver
    import jax

    mesh1 = mt.create_mesh((1, 1), devices=jax.devices()[:1])
    coo = _rating_fixture(7, 50, 30, 4, 0.5, mesh1)
    rep = coo.als(rank=4, iterations=5, lam=0.05, shard=False)
    blk = coo.als(rank=4, iterations=5, lam=0.05, shard=True, segment_block=8)
    np.testing.assert_allclose(blk.user_features.to_numpy(),
                               rep.user_features.to_numpy(),
                               rtol=2e-3, atol=2e-3)
    assert blk.rmse(coo) < 0.3


def test_als_auto_shard_threshold(mesh):
    # the auto heuristic keys on stat-tensor size alone (device count no
    # longer gates it): big segment side -> blocked mode even on 1 device
    from marlin_tpu.ml import als as als_mod

    calls = {}
    orig = als_mod._als_sharded

    def spy(*a, **k):
        calls["sharded"] = True
        return orig(*a, **k)

    als_mod._als_sharded = spy
    try:
        # 300k users x rank 16: stats 4*16*16*300k = 307 MB > 256 MB
        rng = np.random.default_rng(8)
        ui = rng.integers(0, 300_000, 500).astype(np.int32)
        ii = rng.integers(0, 20, 500).astype(np.int32)
        coo = mt.CoordinateMatrix(ui, ii, rng.standard_normal(500).astype(np.float32),
                                  shape=(300_000, 20), mesh=mesh)
        coo.als(rank=16, iterations=1, lam=0.1)
    finally:
        als_mod._als_sharded = orig
    assert calls.get("sharded"), "auto shard heuristic did not engage"


def test_als_sharded_implicit_matches_replicated(mesh):
    rng = np.random.default_rng(3)
    n_users, n_items = 40, 24
    mask = rng.random((n_users, n_items)) < 0.3
    ui, ii = np.nonzero(mask)
    counts = rng.integers(1, 10, len(ui)).astype(np.float32)
    coo = mt.CoordinateMatrix(ui, ii, counts, shape=(n_users, n_items), mesh=mesh)
    rep = coo.als(rank=4, iterations=8, lam=0.1, implicit_prefs=True,
                  alpha=10.0, shard=False)
    sh = coo.als(rank=4, iterations=8, lam=0.1, implicit_prefs=True,
                 alpha=10.0, shard=True, segment_block=8)
    np.testing.assert_allclose(sh.user_features.to_numpy(),
                               rep.user_features.to_numpy(),
                               rtol=5e-3, atol=5e-3)


@pytest.mark.skipif(not os.environ.get("MARLIN_SCALE_TESTS"),
                    reason="multi-GB scale run; set MARLIN_SCALE_TESTS=1")
def test_als_sharded_scale(mesh):
    # VERDICT round-1 #4 done criterion: 2M users × 200k items × rank 64 on
    # the 8-device CPU mesh, per-device stat memory bounded by segment_block
    # (4096·64·64·4 ≈ 67 MB vs the 32 GB full stat tensor), RMSE decreasing.
    rng = np.random.default_rng(0)
    n_users, n_items, rank, nnz = 2_000_000, 200_000, 64, 4_000_000
    ui = rng.integers(0, n_users, nnz).astype(np.int32)
    ii = rng.integers(0, n_items, nnz).astype(np.int32)
    vals = rng.standard_normal(nnz).astype(np.float32)
    coo = mt.CoordinateMatrix(ui, ii, vals, shape=(n_users, n_items), mesh=mesh)
    model = coo.als(rank=rank, iterations=1, lam=0.1, shard=True)
    rmse = model.rmse(coo)
    assert np.isfinite(rmse)
    # one sweep on pure-noise ratings still must beat the unit-sphere init
    from marlin_tpu.ml.als import ALSModel
    init = ALSModel(
        mt.DenseVecMatrix.from_array(np.ones((n_users, rank), np.float32) / rank, mesh),
        mt.DenseVecMatrix.from_array(np.ones((n_items, rank), np.float32) / rank, mesh),
    )
    assert rmse < init.rmse(coo)


def test_carma_split_budget():
    for m, k, n, p in [(100, 100, 100, 8), (10000, 100, 100, 8), (64, 4096, 64, 16)]:
        ms, ks, ns = split_method(m, k, n, p)
        assert ms * ks * ns <= p
        assert ms >= 1 and ks >= 1 and ns >= 1


def test_carma_prefers_long_dim():
    # k is dominant -> k gets the splits (contraction-parallel, psum over k)
    ms, ks, ns = split_method(64, 65536, 64, 8)
    assert ks == 8 and ms == 1 and ns == 1
    # m dominant -> row-parallel, collective-free
    ms, ks, ns = split_method(65536, 64, 64, 8)
    assert ms == 8


def test_near_square_split():
    assert near_square_split(9) == 3
    assert near_square_split(1) >= 1


def test_als_implicit_prefs(mesh):
    # implicit feedback: observed entries should score higher than unobserved
    rng = np.random.default_rng(1)
    n_users, n_items = 25, 15
    mask = rng.random((n_users, n_items)) < 0.3
    ui, ii = np.nonzero(mask)
    counts = rng.integers(1, 10, len(ui)).astype(np.float32)  # interaction counts
    coo = mt.CoordinateMatrix(ui, ii, counts, shape=(n_users, n_items), mesh=mesh)
    model = coo.als(rank=4, iterations=10, lam=0.1, implicit_prefs=True, alpha=10.0)
    u = model.user_features.to_numpy()
    v = model.product_features.to_numpy()
    scores = u @ v.T
    obs_mean = scores[mask].mean()
    unobs_mean = scores[~mask].mean()
    assert obs_mean > unobs_mean + 0.1, (obs_mean, unobs_mean)
