"""Ring matmul, streamed out-of-core paths, ELL SpMM, symmetric_eigs."""

import numpy as np
import pytest

import marlin_tpu as mt
from marlin_tpu.ops.sparse_ell import ell_from_coo, ell_spmm
from marlin_tpu.parallel import ring_matmul, streamed_gramian, streamed_matmul


def test_ring_matmul_matches_oracle(mesh):
    rng = np.random.default_rng(0)
    a = rng.standard_normal((24, 40)).astype(np.float32)
    b = rng.standard_normal((40, 16)).astype(np.float32)
    import jax.numpy as jnp

    c = ring_matmul(jnp.asarray(a), jnp.asarray(b), mesh)
    np.testing.assert_allclose(np.asarray(c), a @ b, rtol=1e-4, atol=1e-4)


def test_ring_matmul_uneven(mesh):
    rng = np.random.default_rng(1)
    a = rng.standard_normal((13, 9)).astype(np.float32)   # neither divisible by 2
    b = rng.standard_normal((9, 5)).astype(np.float32)
    import jax.numpy as jnp

    c = ring_matmul(jnp.asarray(a), jnp.asarray(b), mesh)
    np.testing.assert_allclose(np.asarray(c), a @ b, rtol=1e-4, atol=1e-4)


def test_ring_strategy_via_matrix(mesh):
    rng = np.random.default_rng(2)
    a = rng.standard_normal((32, 32)).astype(np.float32)
    b = rng.standard_normal((32, 32)).astype(np.float32)
    ma = mt.DenseVecMatrix.from_array(a, mesh)
    mb = mt.DenseVecMatrix.from_array(b, mesh)
    out = ma.multiply(mb, strategy="ring")
    np.testing.assert_allclose(out.to_numpy(), a @ b, rtol=1e-4, atol=1e-4)


def test_streamed_matmul(mesh):
    rng = np.random.default_rng(3)
    a = rng.standard_normal((1000, 32)).astype(np.float32)
    b = rng.standard_normal((32, 8)).astype(np.float32)
    out = streamed_matmul(a, b, chunk_rows=128)
    np.testing.assert_allclose(out, a @ b, rtol=1e-4, atol=1e-4)
    # preallocated out buffer (memmap path)
    buf = np.zeros((1000, 8), np.float32)
    streamed_matmul(a, b, chunk_rows=256, out=buf)
    np.testing.assert_allclose(buf, a @ b, rtol=1e-4, atol=1e-4)


def test_streamed_matmul_generator():
    rng = np.random.default_rng(4)
    chunks = [rng.standard_normal((100, 16)).astype(np.float32) for _ in range(5)]
    b = rng.standard_normal((16, 4)).astype(np.float32)
    out = streamed_matmul(iter(chunks), b)
    np.testing.assert_allclose(out, np.concatenate(chunks) @ b, rtol=1e-4, atol=1e-4)


def test_streamed_gramian():
    rng = np.random.default_rng(5)
    a = rng.standard_normal((2000, 24)).astype(np.float32)
    g = streamed_gramian(a, chunk_rows=300)
    np.testing.assert_allclose(g, a.T @ a, rtol=1e-3, atol=1e-3)
    with pytest.raises(ValueError):
        streamed_gramian(iter([]))


def _random_coo(m, n, nnz, seed):
    rng = np.random.default_rng(seed)
    rows = rng.integers(0, m, nnz)
    cols = rng.integers(0, n, nnz)
    vals = rng.standard_normal(nnz).astype(np.float32)
    dense = np.zeros((m, n), np.float32)
    np.add.at(dense, (rows, cols), vals)
    return rows, cols, vals, dense


def test_ell_spmm_matches_dense():
    m, n, p = 200, 150, 40
    rows, cols, vals, dense = _random_coo(m, n, 600, 6)
    ell = ell_from_coo(rows, cols, vals, (m, n))
    assert ell.residual is None
    b = np.random.default_rng(7).standard_normal((n, p)).astype(np.float32)
    out = ell_spmm(ell, b, chunk=64)
    np.testing.assert_allclose(np.asarray(out), dense @ b, rtol=1e-3, atol=1e-3)


def test_ell_overflow_residual():
    # k_width smaller than the max row degree forces the BCOO residual path
    m, n, p = 50, 30, 6
    rows, cols, vals, dense = _random_coo(m, n, 400, 8)
    ell = ell_from_coo(rows, cols, vals, (m, n), k_width=4)
    assert ell.residual is not None
    b = np.random.default_rng(9).standard_normal((n, p)).astype(np.float32)
    out = ell_spmm(ell, b)
    np.testing.assert_allclose(np.asarray(out), dense @ b, rtol=1e-3, atol=1e-3)


def test_sparse_matrix_ell_path(mesh):
    sp = mt.SparseVecMatrix.random(0, 300, 200, density=0.005, mesh=mesh)
    dense = sp.to_numpy()
    b = np.random.default_rng(10).standard_normal((200, 12)).astype(np.float32)
    out_auto = sp.multiply(mt.BlockMatrix.from_array(b, mesh))  # auto -> ell
    out_bcoo = sp.multiply(mt.BlockMatrix.from_array(b, mesh), format="bcoo")
    np.testing.assert_allclose(out_auto.to_numpy(), dense @ b, rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(out_bcoo.to_numpy(), dense @ b, rtol=1e-3, atol=1e-3)


def test_symmetric_eigs_matrix_free(mesh):
    import jax.numpy as jnp

    rng = np.random.default_rng(11)
    q, _ = np.linalg.qr(rng.standard_normal((60, 60)))
    evals_true = np.linspace(30, 0.1, 60)
    sym = (q * evals_true) @ q.T
    sym_j = jnp.asarray(sym.astype(np.float32))
    evals, vecs = mt.linalg.symmetric_eigs(lambda v: sym_j @ v, 60, k=5)
    np.testing.assert_allclose(np.asarray(evals), evals_true[:5], rtol=1e-2)
    # residual check ||Av - λv||
    for i in range(5):
        v = np.asarray(vecs[:, i])
        resid = np.linalg.norm(sym @ v - float(evals[i]) * v)
        assert resid < 0.1


def test_dense_parity_fills(mesh):
    rng = np.random.default_rng(12)
    a = rng.standard_normal((10, 8)).astype(np.float32)
    a[np.abs(a) < 0.8] = 0.0
    m = mt.DenseVecMatrix.from_array(a, mesh)
    sp = m.to_sparse_vec_matrix()
    np.testing.assert_allclose(sp.to_numpy(), a)
    # multiply_by: local @ distributed
    local = rng.standard_normal((4, 10)).astype(np.float32)
    out = m.multiply_by(local)
    np.testing.assert_allclose(out.to_numpy(), local @ a, rtol=1e-4, atol=1e-4)
    try:
        import pandas  # noqa: F401

        df = m.to_dataframe()
        assert df.shape == (10, 8)
    except ImportError:
        pass


def test_streamed_bf16_transfer():
    rng = np.random.default_rng(12)
    a = rng.standard_normal((500, 32)).astype(np.float32)
    b = rng.standard_normal((32, 8)).astype(np.float32)
    out = streamed_matmul(a, b, chunk_rows=128, transfer_dtype="bfloat16")
    # bf16-rounded inputs: expect ~1% relative error on O(1) dot products
    np.testing.assert_allclose(out, a @ b, rtol=5e-2, atol=8e-2)
    g = streamed_gramian(a, chunk_rows=128, transfer_dtype="bfloat16")
    np.testing.assert_allclose(g, a.T @ a, rtol=5e-2, atol=5e-1)
    # exact when not compressed
    g32 = streamed_gramian(a, chunk_rows=128)
    np.testing.assert_allclose(g32, a.T @ a, rtol=1e-3, atol=1e-3)
