"""Paged KV-cache suite: pool mechanics, prefix sharing, chunked prefill.

Layers, bottom up: :class:`PagedKVPool` unit tests (free-list alloc,
refcounts, rolling-hash prefix cache with leaf-first LRU eviction,
copy-on-write splits with a real device page copy), the planner's page
arithmetic, and the paged :class:`ServeEngine` end to end — prefix-share
bit-identity (two requests sharing a system prompt produce outputs
identical to unshared runs), chunked-prefill interleaving and resumability
across injected ``serve.prefill`` faults, the ≤ 3-compiles-per-bucket
bound via the shared ``compile_count`` fixture, page-unit admission, and a
slow chaos soak with worker kills over a paged pool. The generic engine
contracts (exactly-once, drain/close, both backends' acceptance) live in
tests/test_serving.py.
"""

import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from marlin_tpu.models import TransformerLM
from marlin_tpu.models.planner import kv_page_bytes, request_pages
from marlin_tpu.models.transformer import (init_kv_pages, lm_decode_paged,
                                           lm_generate, lm_prefill_paged)
from marlin_tpu.obs import report as obs_report
from marlin_tpu.serving import (
    STATUS_ERROR,
    STATUS_EXPIRED,
    STATUS_OK,
    STATUS_REJECTED,
    PagedKVPool,
    PagePoolExhausted,
    Request,
    ServeEngine,
    Supervisor,
    auto_num_pages,
)
from marlin_tpu.utils import EventLog, faults
from marlin_tpu.utils.faults import RaiseFault, Schedule

HEADS = 2
BUCKETS = ((8, 4), (16, 4))
PAGE_LEN = 4


@pytest.fixture(scope="module")
def params():
    """One tiny LM for the whole module, so every engine shares the jit
    cache (compile-count assertions measure deltas, not absolutes)."""
    return TransformerLM(vocab=32, d_model=16, heads=HEADS, layers=2,
                         seed=9).init_params()


def _engine(params, **kw):
    kw.setdefault("buckets", BUCKETS)
    kw.setdefault("max_batch", 4)
    kw.setdefault("queue_depth", 64)
    kw.setdefault("page_len", PAGE_LEN)
    return ServeEngine(params, HEADS, **kw)


def _ref(params, prompt, steps, heads=HEADS):
    prompt = np.asarray(prompt, np.int32)
    return np.asarray(lm_generate(
        params, prompt, jax.random.key(0), heads=heads,
        max_len=len(prompt) + steps, steps=steps)).tolist()


# ------------------------------------------------------------ pool units


def test_alloc_free_refcount(params):
    pool = PagedKVPool(params, HEADS, num_pages=9, page_len=PAGE_LEN)
    assert pool.capacity == 8 and pool.free_count() == 8
    a = pool.alloc(3)
    b = pool.alloc(2)
    assert len(set(a) | set(b)) == 5 and 0 not in a + b  # dummy never leaves
    assert pool.used_count() == 5
    pool.retain(a)          # a second referent
    pool.release(a)
    assert pool.used_count() == 5  # still held by the second referent
    assert pool.shared_count() == 0
    pool.release(a)
    assert pool.used_count() == 2 and pool.free_count() == 6
    pool.release(b)
    assert pool.used_count() == 0
    with pytest.raises(PagePoolExhausted):
        pool.alloc(pool.capacity + 1)


def test_dummy_page_is_pinned(params):
    pool = PagedKVPool(params, HEADS, num_pages=4, page_len=PAGE_LEN)
    got = pool.alloc(3)
    assert 0 not in got
    pool.release([0, 0])  # table padding slices may include the dummy
    assert pool.free_count() == 0  # no-ops: the dummy never frees


def test_prefix_cache_match_insert_and_limit(params):
    pool = PagedKVPool(params, HEADS, num_pages=32, page_len=PAGE_LEN)
    prompt = np.arange(10, dtype=np.int32)  # share limit = (9//4)*4 = 8
    assert pool.match_prefix(prompt) == (0, [])
    assert pool.misses == 1
    pages = pool.alloc(3)
    assert pool.insert_prefix(prompt, pages) == 2  # 2 full pages cacheable
    sl, shared = pool.match_prefix(prompt)
    assert sl == 8 and shared == pages[:2] and pool.hits == 1
    # the page holding the prompt's LAST token is never shared — it must be
    # re-prefilled (first-token logits) and decode writes continue into it
    exact = np.arange(8, dtype=np.int32)  # page-aligned prompt
    sl, shared2 = pool.match_prefix(exact)
    assert sl == 4  # limit = (7//4)*4: only the first page shares
    pool.release(shared + shared2)
    # a diverging prefix only shares the common pages (rolling-hash chain)
    fork = np.concatenate([np.arange(4), [99, 98, 97, 96], [1, 2]])
    sl, shared3 = pool.match_prefix(fork.astype(np.int32))
    assert sl == 4 and shared3 == pages[:1]
    pool.release(shared3)
    # cache refs keep pages alive after the row's own release
    pool.release(pages)
    assert pool.used_count() == pool.cached_count() == 2


def test_prefix_cache_lru_eviction_is_leaf_first(params):
    pool = PagedKVPool(params, HEADS, num_pages=8, page_len=PAGE_LEN)
    long = np.arange(13, dtype=np.int32)   # 3 cacheable pages (chain r-m-l)
    pages = pool.alloc(4)
    pool.insert_prefix(long, pages)
    pool.release(pages)
    assert pool.cached_count() == 3 and pool.free_count() == 4
    # demand more than free: evicts a cached page LEAF-first (deepest chain
    # entry — evicting a parent would orphan unreachable children)
    got = pool.alloc(5)
    assert len(got) == 5 and pool.evictions == 1
    assert pool.cached_count() == 2
    pool.release(got)
    # leaf went first: the surviving chain still matches two pages
    sl, shared = pool.match_prefix(long)
    assert sl == 8 and len(shared) == 2
    # a cached page with a live reader is NOT evictable; the chain root
    # with a cached child is not either — so nothing can evict here
    with pytest.raises(PagePoolExhausted):
        pool.alloc(pool.free_count() + 1)
    pool.release(shared)
    pool.alloc(pool.free_count() + 1)  # readers gone: the next leaf evicts
    assert pool.evictions == 2 and pool.cached_count() == 1


def test_copy_on_write_splits_shared_page(params):
    pool = PagedKVPool(params, HEADS, num_pages=8, page_len=PAGE_LEN)
    page = pool.alloc(1)[0]
    # mark the page with recognizable contents
    k0 = pool.pages["l0"][0]
    pool.pages["l0"] = (k0.at[page].set(7.0), pool.pages["l0"][1])
    table = np.array([page], np.int32)
    assert not pool.ensure_writable(table, 0)  # sole owner: no copy
    pool.retain([page])                        # now shared
    assert pool.ensure_writable(table, 0)
    fresh = int(table[0])
    assert fresh != page and pool.cow_copies == 1
    # the device copy really happened, and the original kept its referent
    assert bool(jnp.all(pool.pages["l0"][0][fresh] == 7.0))
    assert pool.used_count() == 2 and pool.shared_count() == 0
    assert not pool.ensure_writable(table, 0)  # fresh page: sole owner


def test_page_arithmetic(params):
    # one page: layers(2) x k&v(2) x page_len x kv_heads(2) x dh(8) x f32(4)
    assert kv_page_bytes(params, HEADS, 4) == 2 * 2 * 4 * 2 * 8 * 4
    assert kv_page_bytes(params, HEADS, 4, "bfloat16") == \
        kv_page_bytes(params, HEADS, 4) // 2
    # positions written: [0, n + steps - 1)
    assert request_pages(1, 1, 4) == 1
    assert request_pages(4, 1, 4) == 1   # steps=1: prompt pages only
    assert request_pages(4, 2, 4) == 2   # first decode write opens page 2
    assert request_pages(10, 4, 4) == 4  # ceil(13/4)
    with pytest.raises(ValueError):
        request_pages(0, 1, 4)
    # auto pool sizing covers every bucket's full-width slab extent + slack
    assert auto_num_pages(((8, 4),), 2, 4) == 1 + 2 * (3 + 1)
    with pytest.raises(ValueError, match="num_pages"):
        init_kv_pages(params, 1, 4, HEADS)


# ------------------------------------------------- paged program contracts


def test_chunked_prefill_matches_one_shot(params):
    """Prefilling a prompt in page-aligned chunks writes the same pages —
    and yields the same first token — as one chunk covering everything."""
    prompt = np.arange(16, dtype=np.int32) % 32
    ref = _ref(params, prompt, 4)
    for C in (4, 8, 16):
        pages = init_kv_pages(params, 16, PAGE_LEN, HEADS)
        table = np.zeros(6 + C // PAGE_LEN, np.int32)
        table[:5] = range(1, 6)
        for cs in range(0, 16, C):
            pages, first = lm_prefill_paged(
                params, pages, table, prompt[cs:cs + C], cs, 16,
                heads=HEADS, page_len=PAGE_LEN)
        assert int(first) == ref[16]
        # decode the rest through the paged program
        out = [int(first)]
        positions = np.array([16], np.int32)
        cur = np.array([first], np.int32)
        done = np.array([1], np.int32)
        z = np.zeros(1, np.int32)
        for _ in range(3):
            pages, nxt = lm_decode_paged(
                params, pages, table[None, :5], positions, cur, done,
                z.astype(np.uint32), z.astype(np.float32),
                np.ones(1, np.float32), z, heads=HEADS, page_len=PAGE_LEN)
            out.append(int(np.asarray(nxt)[0]))
            positions += 1
            done += 1
            cur[0] = out[-1]
        assert out == ref[16:]


# --------------------------------------------------------- engine: sharing


def test_prefix_share_bit_identity(params, tmp_path):
    """Two requests sharing a system prompt produce outputs identical to
    unshared runs, the second admission is a prefix-cache hit, and its
    shared pages are counted. The COW/read-sharing invariant end to end."""
    log = EventLog(str(tmp_path / "serve.jsonl"))
    system = (np.arange(12) % 32).astype(np.int32)  # 3 full shared pages
    pa = np.concatenate([system, [7, 7]]).astype(np.int32)
    pb = np.concatenate([system, [9, 8]]).astype(np.int32)
    # unshared references (fresh engine per request: nothing cached)
    ref_a, ref_b = _ref(params, pa, 3), _ref(params, pb, 3)
    with _engine(params, max_batch=2, log=log) as eng:
        ra = eng.submit(Request(prompt=pa, steps=3)).result(timeout=60)
        rb = eng.submit(Request(prompt=pb, steps=3)).result(timeout=60)
        assert ra.status == rb.status == STATUS_OK
        assert ra.tokens.tolist() == ref_a
        assert rb.tokens.tolist() == ref_b
        assert ra.metrics["shared_pages"] == 0
        assert rb.metrics["shared_pages"] == 3  # the system prompt's pages
        snap = eng.metrics.snapshot()
        assert snap["prefix_hits"] == 1 and snap["prefix_misses"] == 1
        # the cache keeps the system prompt's pages alive across retires
        assert eng._kvpool.cached_count() >= 3
        # third sharer, co-resident with nothing: still identical
        rc = eng.submit(Request(prompt=pa, steps=2)).result(timeout=60)
        assert rc.tokens.tolist() == ref_a[:len(pa) + 2]
        assert rc.metrics["shared_pages"] == 3
    # the shared-page reuse skipped prefill work: b's prefill records cover
    # only the tail beyond the shared prefix
    chunks = [r["chunk"] for r in log.read()
              if r.get("kind") == "serve" and r.get("ev") == "prefill"]
    assert [0, 14] in chunks          # a: full prompt from position 0
    assert [12, 2] in chunks          # b: resumed at the shared boundary


def test_report_paging_line(params, tmp_path):
    """obs.report renders the prefix-hit-rate + page-occupancy line from
    the ev="page" stream alone."""
    log = EventLog(str(tmp_path / "serve.jsonl"))
    system = (np.arange(12) % 32).astype(np.int32)
    with _engine(params, log=log) as eng:
        for tail in ([1, 2], [3, 4]):
            h = eng.submit(Request(
                prompt=np.concatenate([system, tail]).astype(np.int32),
                steps=2))
            assert h.result(timeout=60).status == STATUS_OK
    events, skipped = obs_report.load_events(str(tmp_path / "serve.jsonl"))
    assert skipped == 0
    text = obs_report.analyze(events)
    assert "paging: prefix cache 1/2 admissions hit (50.0%" in text
    assert "page occupancy peak" in text


def test_rowlevel_kwarg_is_removed(params):
    """Satellite (ISSUE 18): the deprecated ``rowlevel`` escape hatch is
    gone — passing it (either value) raises a ValueError that points at
    serve_paged, the knob that actually picks a backend now."""
    for val in (False, True):
        with pytest.raises(ValueError, match="serve_paged"):
            _engine(params, rowlevel=val)


# ------------------------------------------------- engine: chunked prefill


def test_chunked_prefill_interleaves_decode(params, tmp_path):
    """The TTFT-under-load contract: a long prompt prefills across worker
    iterations in bounded chunks, and a co-resident short request's decode
    steps run BETWEEN those chunks instead of waiting out the whole
    prefill."""
    log = EventLog(str(tmp_path / "serve.jsonl"))
    eng = _engine(params, log=log, start=False, prefill_chunk=PAGE_LEN)
    try:
        short = eng.submit(Request(prompt=[1, 2], steps=4))
        long = eng.submit(Request(
            prompt=(np.arange(16) % 32).astype(np.int32), steps=2))
        eng.start()
        assert short.result(timeout=60).status == STATUS_OK
        assert long.result(timeout=60).status == STATUS_OK
        assert short.result().tokens.tolist() == _ref(params, [1, 2], 4)
    finally:
        eng.close()
    recs = [r for r in log.read() if r.get("kind") == "serve"
            and r.get("ev") in ("prefill", "step")]
    long_rid = long.request.rid
    chunk_idx = [i for i, r in enumerate(recs)
                 if r["ev"] == "prefill" and r.get("rid") == long_rid]
    assert len(chunk_idx) == 4  # 16 tokens / 4-token chunks, resumable
    starts = [recs[i]["chunk"][0] for i in chunk_idx]
    assert starts == [0, 4, 8, 12]
    between = [r["ev"] for r in recs[chunk_idx[0]:chunk_idx[-1]]]
    assert "step" in between, (
        "no decode step interleaved with the long prompt's chunks")


def test_prefill_fault_retries_resumably(params):
    """Satellite: chunked prefill is resumable across injected
    serve.prefill faults — a mid-prefill fault frees the row's pages, the
    retry re-runs from its (re-matched) shared prefix, and the output
    stays bit-identical. Without attempt budget the request errors and
    the engine keeps serving."""
    prompt = (np.arange(16) % 32).astype(np.int32)
    eng = _engine(params, start=False, prefill_chunk=2 * PAGE_LEN)
    try:
        eng.warmup()
        with faults.injected("serve.prefill", RaiseFault(times=1)):
            h = eng.submit(Request(prompt=prompt, steps=3, max_attempts=2))
            eng.start()
            r = h.result(timeout=60)
        assert r.status == STATUS_OK, (r.status, r.reason)
        assert r.metrics["attempt"] == 2
        assert r.tokens.tolist() == _ref(params, prompt, 3)
        snap = eng.metrics.snapshot()
        assert snap["retries"] == 1
        # budget-exhausted path: error Result, engine unharmed
        with faults.injected("serve.prefill", RaiseFault(times=1)):
            bad = eng.submit(Request(prompt=[5, 6], steps=2))
            r = bad.result(timeout=60)
            assert r.status == STATUS_ERROR and "FaultInjected" in r.reason
        ok = eng.submit(Request(prompt=[5, 6], steps=2)).result(timeout=60)
        assert ok.status == STATUS_OK
        # every page reservation released exactly once: only cache-held
        # pages remain
        pool = eng._kvpool
        assert pool.used_count() == pool.cached_count()
    finally:
        eng.close()
    assert eng._queue.bytes_in_flight == 0


def test_fault_mid_chunk_stream_keeps_neighbors(params):
    """A serve.prefill fault on one row's LATER chunk leaves co-resident
    decoding rows untouched (the paged analog of the slab's
    fault-blast-radius contract)."""
    eng = _engine(params, start=False, prefill_chunk=PAGE_LEN, max_batch=2)
    try:
        eng.warmup()
        neighbor = eng.submit(Request(prompt=[3, 1], steps=4))
        long = eng.submit(Request(
            prompt=(np.arange(16) % 32).astype(np.int32), steps=2))
        # fire on the long prompt's SECOND chunk (the first arrival is its
        # chunk 0; the neighbor's 2-token prefill is one dispatch earlier)
        with faults.injected("serve.prefill",
                             RaiseFault(schedule=Schedule(fire_on=[2]))):
            eng.start()
            rn = neighbor.result(timeout=60)
            rl = long.result(timeout=60)
        assert rn.status == STATUS_OK
        assert rn.tokens.tolist() == _ref(params, [3, 1], 4)
        assert rl.status == STATUS_ERROR and "FaultInjected" in rl.reason
    finally:
        eng.close()
    assert eng._queue.bytes_in_flight == 0


# ----------------------------------------------- compiles, admission units


def test_paged_compiles_bounded_three_per_bucket(params, compile_count):
    """≤ 3 compiled programs per bucket for ANY knob mix: the chunked
    prefill + decode pair per bucket plus ONE pool-wide page-copy program.
    warmup() pays them all; traffic — ragged lengths, shared prefixes,
    eos, mixed sampling knobs, multi-chunk prompts — adds ZERO."""
    from marlin_tpu.models.transformer import kv_page_copy

    probes = [getattr(f, "_cache_size", None)
              for f in (lm_prefill_paged, lm_decode_paged, kv_page_copy)]
    probes = [p for p in probes if p is not None]
    before = sum(p() for p in probes)
    with _engine(params, prefill_chunk=2 * PAGE_LEN) as eng:
        assert eng.warmup() == len(BUCKETS)
        grew = sum(p() for p in probes) - before
        assert grew <= 2 * len(BUCKETS) + 1, \
            f"warmup compiled {grew} paged programs for {BUCKETS}"
        system = (np.arange(8) % 32).astype(np.int32)
        with compile_count() as c:
            hs = [eng.submit(Request(prompt=[1] * n, steps=2))
                  for n in (2, 5, 8, 12, 16)]
            hs.append(eng.submit(Request(
                prompt=np.concatenate([system, [4]]).astype(np.int32),
                steps=3, temperature=0.9, top_p=0.9, top_k=5, seed=11)))
            hs.append(eng.submit(Request(
                prompt=np.concatenate([system, [6]]).astype(np.int32),
                steps=3, eos=1)))
            for h in hs:
                assert h.result(timeout=60).status == STATUS_OK
        assert c.count == 0, \
            f"paged traffic recompiled after warmup ({c.count} compiles)"
    assert eng._queue.bytes_in_flight == 0


def test_unaligned_prefix_tail_compiles_nothing(params, compile_count,
                                                tmp_path):
    """Regression (review): a prefix hit whose shared_len is page- but not
    CHUNK-aligned resumes mid-chunk-grid; the final tail slice must be
    padded back to the compiled chunk width — a narrower chunk would
    compile a fresh program per residual width on the serving hot path."""
    log = EventLog(str(tmp_path / "serve.jsonl"))
    with _engine(params, prefill_chunk=2 * PAGE_LEN, log=log) as eng:
        eng.warmup()
        head = (np.arange(PAGE_LEN) % 32).astype(np.int32)  # one full page
        tail_a = (np.arange(12) % 7 + 20).astype(np.int32)
        tail_b = (np.arange(12) % 5 + 1).astype(np.int32)
        a = np.concatenate([head, tail_a]).astype(np.int32)  # n=16
        b = np.concatenate([head, tail_b]).astype(np.int32)  # n=16
        ra = eng.submit(Request(prompt=a, steps=2)).result(timeout=60)
        assert ra.status == STATUS_OK
        with compile_count() as c:
            # b shares exactly ONE page (shared_len=4, chunk=8): prefill
            # resumes at 4 and its FINAL slice [12:20) runs past the
            # 16-token padded prompt — the short-tail case the padding
            # restores to the compiled width
            rb = eng.submit(Request(prompt=b, steps=2)).result(timeout=60)
        assert rb.status == STATUS_OK
        # read the tally BEFORE the reference decode adds its own program
        assert c.count == 0, \
            f"unaligned prefix tail recompiled ({c.count} compiles)"
        assert rb.metrics["shared_pages"] == 1
        assert rb.tokens.tolist() == _ref(params, b, 2)
    # the chunk stream really took the unaligned path: resume at 4, then
    # a short final chunk from 12
    chunks = [r["chunk"] for r in log.read()
              if r.get("ev") == "prefill" and r.get("rid") == rb.rid]
    assert chunks == [[4, 8], [12, 4]], chunks


def test_second_bucket_failure_after_pool_drop_is_inert(params):
    """Regression (review): when one bucket's decode failure consumes the
    shared slab and drops the pool, a second bucket's failure landing in
    the same step loop must be a no-op — not a KeyError on the cleared
    pools map that masquerades as a worker crash."""
    eng = _engine(params, start=False)
    try:
        pools = {}
        from marlin_tpu.serving.kvpool import PagedGroup

        pool = eng._ensure_kvpool()
        for bucket in BUCKETS:
            pools[bucket] = PagedGroup(bucket, eng.max_batch, PAGE_LEN,
                                       eng._prefill_chunk)
        eng._drop_paged_pool(pool, pools, "slab consumed (simulated)")
        assert pools == {} and eng._kvpool is None
        # the second bucket's handler observes the drop and returns
        eng._fail_paged_bucket(pool, pools, BUCKETS[1],
                               RuntimeError("late"))
        # a STALE generation's drop must not clear the live pool: rebind,
        # then drop with the old (dead) pool object — the live reference
        # survives
        live = eng._ensure_kvpool()
        eng._drop_paged_pool(pool, {}, "stale straggler")
        assert eng._kvpool is live
    finally:
        eng.close()


def test_page_unit_admission(params):
    """Admission charges actual pages, not the bucket worst case: a short
    request's reservation is its own extent, an impossible request rejects
    at submit, and the byte budget counts page units."""
    unit = kv_page_bytes(params, HEADS, PAGE_LEN)
    eng = _engine(params, start=False,
                  num_pages=1 + request_pages(2, 2, PAGE_LEN) * 3,
                  hbm_budget_bytes=0)
    try:
        # pool capacity (3 short requests' worth) gates impossible shapes
        r = eng.submit(Request(prompt=[1] * 16, steps=4)).result(timeout=1)
        assert r.status == STATUS_REJECTED and "KV pages" in r.reason
    finally:
        eng.close()
    eng = _engine(params, start=False, hbm_budget_bytes=10 * unit)
    try:
        # a (16, 4) bucket row would cost 5 pages' bytes under slab-era
        # worst-case accounting; paged charges this 2-token request 1 page
        h = eng.submit(Request(prompt=[1, 2], steps=2))
        assert eng._queue.bytes_in_flight == \
            request_pages(2, 2, PAGE_LEN) * unit
        for _ in range(9):  # nine more single-page requests fit
            eng.submit(Request(prompt=[1, 2], steps=2))
        r = eng.submit(Request(prompt=[1, 2], steps=2)).result(timeout=1)
        assert r.status == STATUS_REJECTED and "HBM" in r.reason
        eng.start()
        eng.drain()
        assert h.result(timeout=60).status == STATUS_OK
    finally:
        eng.close()
    assert eng._queue.bytes_in_flight == 0


def test_every_retirement_path_frees_pages(params):
    """Satellite regression: eos / steps / submit-expiry / dispatch-expiry
    / prefill-fault / decode-fault / drain each release the row's pages
    exactly once — afterwards the pool holds only prefix-cache pages and
    the admission gate is fully drained (the page-unit mirror of PR 4's
    expiring-burst test, per path)."""
    from tests.test_serving import FakeClock

    clock = FakeClock()
    eng = _engine(params, clock=clock, start=False, max_batch=2)
    try:
        eng.warmup()
        gen = _ref(params, [5, 3], 4)[2:]
        hs = [
            eng.submit(Request(prompt=[5, 3], steps=4, eos=gen[1])),  # eos
            eng.submit(Request(prompt=[1, 2], steps=2)),            # steps
            eng.submit(Request(prompt=[1, 2], steps=2, deadline=-1.0)),
            eng.submit(Request(prompt=[1, 2], steps=2, deadline=5.0)),
        ]
        clock.advance(10.0)  # expires the deadline=5.0 row at dispatch
        with faults.injected("serve.decode_step", RaiseFault(times=1)):
            eng.start()
            statuses = [h.result(timeout=60).status for h in hs]
        assert statuses[2] == statuses[3] == STATUS_EXPIRED
        assert set(statuses[:2]) <= {STATUS_OK, STATUS_ERROR}
        with faults.injected("serve.prefill", RaiseFault(times=1)):
            bad = eng.submit(Request(prompt=[9, 9], steps=2))
            assert bad.result(timeout=60).status == STATUS_ERROR
        tail = eng.submit(Request(prompt=[8, 8], steps=2))
        eng.drain()
        assert tail.result(timeout=60).status == STATUS_OK
        pool = eng._kvpool
        assert pool is not None
        assert pool.used_count() == pool.cached_count()  # rows all freed
        assert pool.shared_count() == 0
        audit = eng.kvpool_audit()  # every invariant, not just the counts
        assert audit["ok"], audit["errors"]
    finally:
        eng.close()
    assert eng.pending() == 0
    assert eng._queue.bytes_in_flight == 0


def test_crash_recovery_rebuilds_pool_and_preserves_identity(params):
    """Supervisor recovery over a paged pool: the pool (and its prefix
    cache) is dropped and rebuilt zeroed, live rows requeue with their
    page-unit reservations carried, and retried greedy output stays
    bit-identical — including a row that had prefix-shared pages in the
    dead pool."""
    system = (np.arange(12) % 32).astype(np.int32)
    pa = np.concatenate([system, [7]]).astype(np.int32)
    eng = _engine(params, max_batch=2)
    eng.warmup()
    sup = Supervisor(eng, backoff_s=0.005, poll_s=0.02)
    try:
        warm = eng.submit(Request(prompt=pa, steps=2)).result(timeout=60)
        assert warm.status == STATUS_OK  # seeds the prefix cache
        pool_before = eng._kvpool
        with faults.injected("serve.worker_crash", RaiseFault(times=1)):
            hs = [eng.submit(Request(prompt=pa, steps=3, max_attempts=3)),
                  eng.submit(Request(prompt=[4, 2], steps=3,
                                     max_attempts=3))]
            results = [h.result(timeout=120) for h in hs]
        assert all(r.status == STATUS_OK for r in results), \
            [(r.status, r.reason) for r in results]
        assert results[0].tokens.tolist() == _ref(params, pa, 3)
        assert results[1].tokens.tolist() == _ref(params, [4, 2], 3)
        assert sup.restart_count >= 1
        assert eng._kvpool is not pool_before  # rebuilt zeroed
    finally:
        sup.close()
        eng.close()
    assert eng._queue.bytes_in_flight == 0


# ------------------------------------------------------------- chaos soak


@pytest.mark.slow
def test_paging_soak_with_worker_kills(params):
    """Slow chaos soak: shared-prefix traffic over a paged pool while
    seeded serve.worker_crash kills the worker — every handle terminal
    exactly once, ok results bit-identical, pool accounting clean."""
    rng = np.random.default_rng(17)
    system = (np.arange(12) % 32).astype(np.int32)
    n_req = 160
    eng = _engine(params, queue_depth=n_req, num_pages=2048)
    eng.warmup()
    sup = Supervisor(eng, backoff_s=0.002, poll_s=0.01,
                     restart_max=1000, restart_window_s=1e6)
    handles, lock = [], threading.Lock()

    def submitter(seed):
        r = np.random.default_rng(seed)
        for _ in range(n_req // 4):
            if r.random() < 0.5:  # half the traffic shares the system prompt
                prompt = np.concatenate(
                    [system, r.integers(0, 32, int(r.integers(1, 4)))])
            else:
                prompt = r.integers(0, 32, int(r.integers(1, 17)))
            h = eng.submit(Request(prompt=prompt.astype(np.int32),
                                   steps=int(r.integers(1, 5)),
                                   max_attempts=8))
            with lock:
                handles.append(h)
            time.sleep(0.001)

    try:
        with faults.injected(
                "serve.worker_crash",
                RaiseFault(times=-1, schedule=Schedule(seed=5, rate=0.02))):
            threads = [threading.Thread(target=submitter, args=(40 + i,))
                       for i in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            eng.drain()
            audit = eng.kvpool_audit()  # chaos postcondition: pages leak
            assert audit["ok"], audit["errors"]  # nowhere, ever
        results = [h.result(timeout=600) for h in handles]
    finally:
        sup.close()
        eng.close()
    assert len(results) == n_req and all(h.done() for h in handles)
    statuses = [r.status for r in results]
    assert set(statuses) <= {STATUS_OK, STATUS_ERROR}
    assert statuses.count(STATUS_OK) >= n_req * 0.9
    for h, r in zip(handles, results):
        if r.status == STATUS_OK:
            steps = len(r.tokens) - len(h.request.prompt)
            ref = _ref(params, h.request.prompt, steps)
            assert r.tokens.tolist() == ref[:len(r.tokens)]
    snap = eng.metrics.snapshot()
    assert snap["completed"] == statuses.count(STATUS_OK)
    assert snap["prefix_hits"] > 0  # sharing really happened under chaos
    assert eng.pending() == 0
    assert eng._queue.bytes_in_flight == 0
