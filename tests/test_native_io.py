"""Native C++ text-IO parity with the Python parser."""

import numpy as np
import pytest

from marlin_tpu import native


@pytest.fixture(scope="module")
def lib_ok():
    if not native.available():
        pytest.skip("native textio library not built (no toolchain?)")
    return True


def test_native_roundtrip(tmp_path, lib_ok):
    rng = np.random.default_rng(0)
    a = rng.standard_normal((37, 11))
    p = str(tmp_path / "m.txt")
    assert native.save_matrix_text(p, a)
    back = native.load_matrix_text(p)
    np.testing.assert_allclose(back, a)  # %.17g roundtrips f64 exactly


def test_native_matches_python_parser(tmp_path, lib_ok):
    p = str(tmp_path / "m.txt")
    with open(p, "w") as f:
        f.write("0:1.5 2.0,3.25\n2:4.0, 5.0 6.5\n")  # mixed separators + row gap
    from marlin_tpu.io.text import _iter_lines, _rows_from_lines

    py = _rows_from_lines(_iter_lines(p))
    nat = native.load_matrix_text(p)
    np.testing.assert_allclose(nat, py)
    assert nat.shape == (3, 3)
    assert nat[1].sum() == 0  # missing row -> zeros


def test_native_ragged_rows(tmp_path, lib_ok):
    p = str(tmp_path / "r.txt")
    with open(p, "w") as f:
        f.write("0:1.0\n1:2.0,3.0,4.0\n")
    nat = native.load_matrix_text(p)
    assert nat.shape == (2, 3)
    np.testing.assert_allclose(nat, [[1.0, 0.0, 0.0], [2.0, 3.0, 4.0]])


def test_framework_uses_native_path(tmp_path, mesh, lib_ok):
    import marlin_tpu as mt

    rng = np.random.default_rng(1)
    a = rng.standard_normal((20, 6)).astype(np.float32)
    m = mt.DenseVecMatrix.from_array(a, mesh)
    p = str(tmp_path / "n.txt")
    m.save_to_file_system(p)
    loaded = mt.load_matrix_file(p, mesh)
    np.testing.assert_allclose(loaded.to_numpy(), a, rtol=1e-6, atol=1e-6)


def test_native_speed_sanity(tmp_path, lib_ok):
    # not a benchmark, just "doesn't blow up on a few MB"
    rng = np.random.default_rng(2)
    a = rng.standard_normal((2000, 200))
    p = str(tmp_path / "big.txt")
    native.save_matrix_text(p, a)
    back = native.load_matrix_text(p)
    np.testing.assert_allclose(back, a)


def test_native_corrupt_token_raises(tmp_path, lib_ok):
    p = str(tmp_path / "bad.txt")
    with open(p, "w") as f:
        f.write("0:1.0,2.0,x,4.0\n")
    with pytest.raises(ValueError):
        native.load_matrix_text(p)


def test_native_bad_row_index_raises(tmp_path, lib_ok):
    p = str(tmp_path / "badrow.txt")
    with open(p, "w") as f:
        f.write("0:1.0,2.0\nx:9.0,9.0\n1:3.0,4.0\n")
    with pytest.raises(ValueError):
        native.load_matrix_text(p)


def test_native_colonless_line_raises(tmp_path, lib_ok):
    p = str(tmp_path / "csv.txt")
    with open(p, "w") as f:
        f.write("1.0,2.0\n3.0,4.0\n")
    with pytest.raises(ValueError):
        native.load_matrix_text(p)
    # blank lines are still fine
    p2 = str(tmp_path / "blank.txt")
    with open(p2, "w") as f:
        f.write("0:1.0,2.0\n\n1:3.0,4.0\n")
    np.testing.assert_allclose(native.load_matrix_text(p2), [[1, 2], [3, 4]])


def test_native_coo_roundtrip(tmp_path, mesh, lib_ok):
    import marlin_tpu as mt

    rng = np.random.default_rng(2)
    nnz = 5000
    ri = rng.integers(0, 400, nnz)
    ci = rng.integers(0, 300, nnz)
    vals = rng.standard_normal(nnz)
    # exercise extremes of the shortest-repr formatter (within f32 range —
    # the loader narrows to f32, matching the reference's Float entries)
    vals[:4] = [0.0, 1e-30, -1e30, 0.1]
    p = str(tmp_path / "coo.txt")
    assert native.save_coo_text(p, ri, ci, vals)
    coo = mt.load_coordinate_matrix(p, mesh=mesh)
    np.testing.assert_array_equal(np.asarray(coo.row_indices), ri)
    np.testing.assert_array_equal(np.asarray(coo.col_indices), ci)
    np.testing.assert_allclose(np.asarray(coo.values, np.float64), vals,
                               rtol=1e-7)  # loader may narrow to f32


def test_coordinate_matrix_save_uses_native(tmp_path, mesh, lib_ok):
    import marlin_tpu as mt

    coo = mt.CoordinateMatrix([0, 1, 7], [2, 0, 5], [1.5, -2.25, 3.0],
                              shape=(8, 6), mesh=mesh)
    p = str(tmp_path / "saved.txt")
    coo.save_to_file_system(p)
    text = open(p).read()
    assert text == "0 2 1.5\n1 0 -2.25\n7 5 3\n"
    back = mt.load_coordinate_matrix(p, shape=(8, 6), mesh=mesh)
    np.testing.assert_allclose(np.asarray(back.values), [1.5, -2.25, 3.0])


def test_native_read_error_surfaces(tmp_path, lib_ok):
    # FileBuf::read checks fread against the stat'd size: an unreadable
    # "file" (a directory — fopen succeeds on Linux, fread fails EISDIR)
    # must raise an OSError, not return a garbage/empty matrix. Regression
    # for the unchecked-fread bug where short reads parsed as truncated data.
    with pytest.raises(OSError):
        native.load_matrix_text(str(tmp_path))


def test_build_failure_warns_once_and_is_queryable(monkeypatch, tmp_path):
    """A failed native build must be loud (one RuntimeWarning carrying the
    captured stderr) and queryable (build_error()), never a silent fallback
    to the 100x-slower Python plane."""
    import warnings

    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_chunk_lib", None)
    monkeypatch.setattr(native, "_tried_build", False)
    monkeypatch.setattr(native, "_build_error", None)
    monkeypatch.setattr(native, "_warned", False)
    monkeypatch.setattr(native, "_SO", str(tmp_path / "absent.so"))
    monkeypatch.setattr(native, "_CHUNK_SO", str(tmp_path / "absent2.so"))

    class _Proc:
        returncode = 2
        stdout = ""
        stderr = "g++: fatal error: no such toolchain"

    monkeypatch.setattr(native.subprocess, "run",
                        lambda *a, **k: _Proc())
    with pytest.warns(RuntimeWarning, match="no such toolchain"):
        assert native._load() is None
    assert not native.available()
    assert "make exited 2" in native.build_error()
    assert "no such toolchain" in native.build_error()
    # the warning fires ONCE; later probes (either library) stay quiet
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert native._load_chunkstore() is None
        assert not native.chunkstore_available()


def test_native_out_of_range_tokens(tmp_path, lib_ok):
    # float('1e400') -> inf in Python; the native parser must agree, not
    # reject the file (from_chars result_out_of_range fallback)
    p = str(tmp_path / "big.txt")
    with open(p, "w") as f:
        f.write("0:1e400,-1e400,1e-400,2.5\n")
    nat = native.load_matrix_text(p)
    assert nat[0, 0] == np.inf and nat[0, 1] == -np.inf
    assert nat[0, 2] == 0.0 and nat[0, 3] == 2.5
