"""Elastic fleet suite (serving/fleet.py, the router's elastic membership,
the supervisor's warmup exemption; docs/serving.md "Elastic fleet",
docs/robustness.md).

The acceptance bars, bottom up:

- **Routing math**: weighted rendezvous at weight 1.0 orders replicas
  identically to the classic digest score (the prefix-affinity tests'
  invariant survives), removing a replica moves ONLY the keys it owned,
  and deweighting one replica re-places only (a fraction of) its own keys
  — minimal-churn membership changes by construction.
- **Membership**: a retired replica leaves every rendezvous score list
  and readiness snapshot BEFORE its rows move; a scale-out replica joins
  warmed, with a brand-new supervisor (fresh restart-breaker window) and
  a never-reused index; a warmup in progress is exempt from the watchdog
  while crash detection stays on.
- **Controller state machine** (FakeRouter + injected clock, fully
  deterministic): hysteresis, cooldown, flap damping, min/max bounds,
  single-flight actions with timeout accounting, error degradation to
  no-op, and the /debug/fleet payload.
- **Chaos**: a fault on any ``serve.fleet`` leg (spawn/join/retire/shed)
  aborts the scale event atomically — fleet unchanged, zero dropped or
  double-delivered requests, zero token-0 restarts; the slow soak drives
  >= 6 real scale events (out, in, rebalance, one killed mid-event each,
  plus a controller killed and rebuilt mid-fleet) under ~64 req/s.
"""

import itertools
import random
import threading
import time

import numpy as np
import pytest

import jax

from marlin_tpu.models import TransformerLM
from marlin_tpu.models.transformer import lm_generate
from marlin_tpu.obs import memledger
from marlin_tpu.obs.exposition import fleet_payload
from marlin_tpu.serving import (
    STATUS_OK,
    FleetController,
    Request,
    Router,
    ServeEngine,
)
from marlin_tpu.serving.router import _rendezvous_score, _weighted_score
from marlin_tpu.serving.supervisor import Supervisor
from marlin_tpu.utils import faults
from marlin_tpu.utils.faults import DelayFault, RaiseFault

HEADS = 2
BUCKETS = ((8, 8), (16, 8))
PAGE_LEN = 4


@pytest.fixture(scope="module")
def params():
    return TransformerLM(vocab=32, d_model=16, heads=HEADS, layers=2,
                         seed=9).init_params()


def _engine(params, **kw):
    kw.setdefault("buckets", BUCKETS)
    kw.setdefault("max_batch", 4)
    kw.setdefault("queue_depth", 64)
    kw.setdefault("page_len", PAGE_LEN)
    kw.setdefault("num_pages", 256)
    kw.setdefault("paged", True)
    return ServeEngine(params, HEADS, **kw)


def _factory(params, **kw):
    def make():
        eng = _engine(params, **kw)
        # a scale-out replica binds live traffic the moment it joins the
        # ring: an unwarmed one would sit in first-traffic XLA compile
        eng.warmup()
        return eng
    return make


def _ref(params, prompt, steps, heads=HEADS):
    prompt = np.asarray(prompt, np.int32)
    return np.asarray(lm_generate(
        params, prompt, jax.random.key(0), heads=heads,
        max_len=len(prompt) + steps, steps=steps)).tolist()


class FakeClock:
    """Injectable monotonic clock for deterministic controller tests."""

    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


_fake_ids = itertools.count()


class FakeRouter:
    """The controller's full contract surface, with scripted burn/loads."""

    def __init__(self, n=1, burn=0.0, loads=None):
        self._name = f"fake-router-{next(_fake_ids)}"
        self.n = n
        self.burn = burn
        self.loads = loads
        self.weights = {}
        self.calls = []
        self.fail = None

    def replica_count(self):
        return self.n

    def replica_view(self):
        loads = self.loads if self.loads is not None else [0] * self.n
        return [{"replica": i, "state": "accepting", "load": loads[i],
                 "weight": self.weights.get(i, 1.0), "restarts": 0}
                for i in range(self.n)]

    def _fleet_slo(self):
        return {"objectives": [{"burn_rate": self.burn}]}

    def add_replica(self):
        self.calls.append("scale_out")
        if self.fail is not None:
            raise self.fail
        self.n += 1
        return self.n - 1

    def retire_replica(self, idx=None):
        self.calls.append("scale_in")
        if self.fail is not None:
            raise self.fail
        self.n -= 1
        return self.n

    def shed_weight(self, idx=None, frac=0.5):
        self.calls.append("rebalance")
        if self.fail is not None:
            raise self.fail
        i = 0 if idx is None else idx
        self.weights[i] = self.weights.get(i, 1.0) * (1.0 - frac)
        return i, self.weights[i]


def _ctl(router, clock, **kw):
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 4)
    kw.setdefault("eval_interval_s", 1.0)
    kw.setdefault("out_burn", 1.0)
    kw.setdefault("in_burn", 0.1)
    kw.setdefault("hysteresis", 2)
    kw.setdefault("cooldown_s", 0.0)
    kw.setdefault("flap_window_s", 0.0)
    kw.setdefault("threaded", False)
    return FleetController(router, clock=clock, **kw)


class _CaptureLog:
    def __init__(self):
        self.records = []

    def event(self, kind, **fields):
        self.records.append(dict(fields, kind=kind))


# --------------------------------------------------------- routing math


def _owner(key, members, weights=None):
    weights = weights or {}
    return max(members,
               key=lambda i: _weighted_score(key, i, weights.get(i, 1.0)))


def test_weighted_hrw_matches_classic_at_weight_one():
    """At weight 1.0 the weighted transform is order-preserving over the
    digest score — every existing affinity placement is unchanged by the
    elastic-membership refactor."""
    members = [0, 1, 2, 3, 7]
    for i in range(60):
        key = f"prefix-{i}".encode()
        classic = max(members, key=lambda m: _rendezvous_score(key, m))
        assert _owner(key, members) == classic


def test_remove_and_deweight_move_only_owned_keys():
    """Minimal re-placement churn: dropping replica 2 moves ONLY keys it
    owned; halving its weight moves only a fraction of its own keys and
    nobody else's — the satellite-1 churn bar."""
    keys = [f"prefix-{i}".encode() for i in range(400)]
    members = [0, 1, 2, 3]
    before = {k: _owner(k, members) for k in keys}
    owned = [k for k in keys if before[k] == 2]
    assert owned  # the fixture must actually exercise replica 2

    after = {k: _owner(k, [0, 1, 3]) for k in keys}
    for k in keys:
        if before[k] != 2:
            assert after[k] == before[k], "non-owned key moved on removal"

    shed = {k: _owner(k, members, {2: 0.5}) for k in keys}
    moved = [k for k in keys if shed[k] != before[k]]
    assert moved, "halving a weight must re-place some of its keys"
    assert all(before[k] == 2 for k in moved), \
        "deweighting replica 2 moved a key it never owned"
    frac = len(moved) / len(owned)
    assert 0.2 < frac < 0.8, frac  # ~1 - 1/(2 - w): a share, not all


# ----------------------------------------------------- router membership


def test_retired_replica_leaves_candidates_before_rows_move(params):
    """retire_replica pulls the replica out of every rendezvous/readiness
    list BEFORE migration starts (observed from inside the migrate hook),
    removes it from the fleet, and never reuses its index."""
    router = Router(_factory(params), replicas=3, supervise=False,
                    rng=random.Random(5))
    try:
        seen = {}
        orig = router._migrate_out

        def spy(rep):
            seen["candidates"] = [r.idx for r in router._candidates()]
            return orig(rep)

        router._migrate_out = spy
        retired = router.retire_replica(1)
        assert retired == 1
        assert 1 not in seen["candidates"], \
            "retiring replica still routable while its rows moved"
        assert router.replica_count() == 2
        assert [v["replica"] for v in router.replica_view()] == [0, 2]
        # stable indices: the next spawn gets a NEVER-used index, not 1 —
        # rendezvous keys the index, and reuse would inherit dead affinity
        idx = router.add_replica()
        assert idx == 3
    finally:
        router.close()


def test_retire_refuses_last_replica(params):
    router = Router(_factory(params), replicas=1, supervise=False)
    try:
        with pytest.raises(RuntimeError, match="last replica"):
            router.retire_replica()
        assert router.replica_count() == 1
    finally:
        router.close()


def test_scale_out_replica_fresh_breaker_window(params):
    """A scaled-out replica must NOT inherit a struggling peer's restart
    history: it gets its own supervisor with an empty sliding window and
    a closed breaker — the satellite-2 regression bar."""
    router = Router(_factory(params), replicas=1,
                    supervisor_kw=dict(backoff_s=0.005, poll_s=0.02))
    try:
        sup0 = router._replicas[0].supervisor
        # salt the incumbent's window as if it had been crash-looping
        sup0._restarts.extend([time.monotonic()] * 2)
        sup0.restart_count = 2
        idx = router.add_replica()
        rep = next(r for r in router._replicas if r.idx == idx)
        assert rep.supervisor is not None and rep.supervisor is not sup0
        assert len(rep.supervisor._restarts) == 0
        assert rep.supervisor.restart_count == 0
        assert rep.supervisor.breaker_open is False
        assert rep.restarts == 0
        assert rep.engine._started is False or rep.ready()
        # the fresh replica serves for real
        h = rep.engine.submit(Request(prompt=[1, 2, 3], steps=4))
        assert h.result(timeout=60).status == STATUS_OK
    finally:
        router.close()


def test_watchdog_exempts_warmup_but_keeps_crash_detection(params):
    """A stale heartbeat with pending work is 'stuck' — UNLESS a warmup
    is in progress (first-compile latency outlasts any sane watchdog).
    The same staleness recovers the moment the warmup flag drops."""
    eng = _engine(params)
    sup = Supervisor(eng, watchdog_s=0.05, start=False, poll_s=0.01,
                     backoff_s=0.0)
    try:
        eng.warmup()
        assert eng._warming is False  # the flag never leaks past warmup
        eng.start()
        time.sleep(0.05)  # worker parks idle (heartbeat stays put)
        eng.pending = lambda: 1                      # stuck-looking state:
        eng._heartbeat = time.monotonic() - 99.0     # pending + stale pulse
        eng._warming = True
        assert sup.check()  # mid-warmup: watchdog holds fire
        assert sup.restart_count == 0
        eng._warming = False
        assert sup.check()  # warmup over: same staleness recovers
        assert sup.restart_count == 1
    finally:
        del eng.pending
        sup.close()
        eng.close()


def test_shed_weight_reroutes_and_floors(params):
    """shed_weight shrinks exactly one replica's weight (visible in the
    replica view), repeated sheds floor instead of hitting zero, and the
    replica stays in the candidate list as a failover target."""
    router = Router(_factory(params), replicas=2, supervise=False,
                    rng=random.Random(1))
    try:
        idx, w = router.shed_weight(idx=0, frac=0.5)
        assert idx == 0 and w == pytest.approx(0.5)
        view = {v["replica"]: v["weight"] for v in router.replica_view()}
        assert view[0] == pytest.approx(0.5) and view[1] == 1.0
        for _ in range(20):
            _, w = router.shed_weight(idx=0, frac=0.9)
        assert w >= 0.05  # the floor keeps it scoreable
        assert 0 in [r.idx for r in router._candidates()]
    finally:
        router.close()


def test_scale_events_emit_and_merge_counters(params):
    """replica_add / replica_retire / rebalance land in the EventLog and
    a retired replica's counters fold into the router snapshot (work it
    served is not forgotten with it)."""
    log = _CaptureLog()
    router = Router(_factory(params), replicas=2, supervise=False,
                    rng=random.Random(2), log=log)
    try:
        hs = [router.submit(Request(prompt=[3, i % 4 + 1], steps=3))
              for i in range(6)]
        router.drain()
        assert all(h.result(timeout=60).status == STATUS_OK for h in hs)
        steps_before = router.snapshot()["steps"]
        router.add_replica()
        router.shed_weight(idx=0, frac=0.25)
        router.retire_replica(0)
        assert router.snapshot()["steps"] >= steps_before
        evs = [r.get("ev") for r in log.records if r["kind"] == "serve"]
        assert "replica_add" in evs
        assert "rebalance" in evs
        assert "replica_retire" in evs
    finally:
        router.close()


# ----------------------------------------- scale events under live load


def test_scale_in_lossless_under_load(params):
    """The scale-in acceptance: retiring a replica with live mid-stream
    rows and a queued backlog drops NOTHING and restarts NOTHING from
    token 0 — rows migrate mid-decode (migrated_in > 0, retries == 0)
    and every output is bit-identical to the reference."""
    router = Router(_factory(params, max_batch=8, queue_depth=512,
                             num_pages=512),
                    replicas=2,
                    supervisor_kw=dict(backoff_s=0.005, poll_s=0.02),
                    rng=random.Random(7))
    handles, lock = [], threading.Lock()
    stop = threading.Event()

    def pump():
        i = 0
        while not stop.is_set():
            h = router.submit(Request(prompt=[5, 1 + i % 4], steps=4))
            with lock:
                handles.append(h)
            i += 1
            time.sleep(0.015)

    thread = threading.Thread(target=pump)
    try:
        thread.start()
        time.sleep(0.1)
        # pin live mid-stream rows on the retiring replica: long rows land
        # in the (16, 8) bucket the pump never touches, and the match-gated
        # delay wedges just the worker decoding them
        first = router._replicas[0].engine
        with faults.injected("serve.decode_step",
                             DelayFault(seconds=0.5, times=1,
                                        match="16x8")):
            with lock:
                handles.extend(first.submit(
                    Request(prompt=[2, 4, 6, 1, 3, 5, 2, 4, 6], steps=8))
                    for _ in range(4))
            time.sleep(0.05)
            retired = router.retire_replica(0)
        stop.set()
        thread.join()
        router.drain()
        assert retired == 0 and router.replica_count() == 1
        results = [h.result(timeout=120) for h in handles]
        assert len(results) >= 8
        for h, r in zip(handles, results):
            assert r.status == STATUS_OK, (r.status, r.reason)
            assert r.tokens.tolist() == _ref(params, h.request.prompt,
                                             h.request.steps)
        snap = router.snapshot()
        assert snap["migrated_in"] >= 1  # rows moved mid-stream...
        assert snap["retries"] == 0      # ...and none restarted at token 0
        for rep in router._replicas:
            audit = rep.engine.kvpool_audit()
            assert audit["ok"], audit["errors"]
    finally:
        stop.set()
        router.close()


def test_scale_out_serves_immediately(params):
    """A scaled-out replica joins warmed and takes traffic: after the
    join, submits spread across both replicas and everything completes."""
    router = Router(_factory(params), replicas=1,
                    supervisor_kw=dict(backoff_s=0.005, poll_s=0.02),
                    rng=random.Random(4))
    try:
        hs = [router.submit(Request(prompt=[1 + i % 6, 2], steps=3))
              for i in range(4)]
        idx = router.add_replica()
        assert idx == 1 and router.replica_count() == 2
        assert all(r.ready() for r in router._replicas)
        hs += [router.submit(Request(prompt=[2, 1 + i % 6], steps=3))
               for i in range(12)]
        router.drain()
        for h in hs:
            r = h.result(timeout=120)
            assert r.status == STATUS_OK, (r.status, r.reason)
            assert r.tokens.tolist() == _ref(params, h.request.prompt,
                                             h.request.steps)
        for rep in router._replicas:
            audit = rep.engine.kvpool_audit()
            assert audit["ok"], audit["errors"]
    finally:
        router.close()


# --------------------------------------------- controller state machine


def test_hysteresis_gates_scale_out():
    r = FakeRouter(n=1, burn=5.0)
    clk = FakeClock()
    ctl = _ctl(r, clk, hysteresis=3)
    try:
        for _ in range(2):
            d = ctl.tick()
            assert d["action"] is None and d["reason"] == "steady"
            clk.advance(1.0)
        d = ctl.tick()
        assert d["action"] == "scale_out"
        assert r.calls == ["scale_out"] and r.n == 2
        assert ctl._last_action["outcome"] == "ok"
    finally:
        ctl.close()


def test_burn_dip_resets_the_streak():
    r = FakeRouter(n=1, burn=5.0)
    clk = FakeClock()
    ctl = _ctl(r, clk, hysteresis=2)
    try:
        ctl.tick()
        clk.advance(1.0)
        r.burn = 0.5  # between in_burn and out_burn: streaks reset
        d = ctl.tick()
        assert d["reason"] == "steady"
        clk.advance(1.0)
        r.burn = 5.0
        d = ctl.tick()  # streak restarts at 1, not 2
        assert d["action"] is None
        assert r.calls == []
    finally:
        ctl.close()


def test_bounds_at_max_and_at_min():
    clk = FakeClock()
    r = FakeRouter(n=4, burn=5.0)
    ctl = _ctl(r, clk, hysteresis=1, max_replicas=4)
    try:
        d = ctl.tick()
        assert d["action"] is None and d["reason"] == "at-max"
        assert r.calls == []
    finally:
        ctl.close()
    r2 = FakeRouter(n=2, burn=0.0)
    ctl2 = _ctl(r2, clk, hysteresis=1, min_replicas=2)
    try:
        clk.advance(1.0)
        d = ctl2.tick()
        assert d["action"] is None and d["reason"] == "at-min"
        assert r2.calls == []
    finally:
        ctl2.close()


def test_cooldown_spaces_actions():
    r = FakeRouter(n=1, burn=5.0)
    clk = FakeClock()
    ctl = _ctl(r, clk, hysteresis=1, cooldown_s=5.0)
    try:
        assert ctl.tick()["action"] == "scale_out"
        clk.advance(1.0)
        d = ctl.tick()
        assert d["action"] is None and d["reason"] == "cooldown"
        clk.advance(5.0)
        assert ctl.tick()["action"] == "scale_out"
        assert r.calls == ["scale_out", "scale_out"]
    finally:
        ctl.close()


def test_flap_damping_suppresses_reversal():
    """A scale-in right after a scale-out (inside the flap window) is an
    oscillating signal, not a trend: the reversal is suppressed and
    recorded, the fleet does not thrash; past the window it proceeds."""
    r = FakeRouter(n=1, burn=5.0)
    clk = FakeClock()
    ctl = _ctl(r, clk, hysteresis=1, flap_window_s=30.0)
    try:
        assert ctl.tick()["action"] == "scale_out"
        clk.advance(1.0)
        r.burn = 0.0  # immediate slack: wants to reverse
        d = ctl.tick()
        assert d["action"] == "scale_in" and d["outcome"] == "damped"
        assert r.calls == ["scale_out"]  # nothing actually retired
        clk.advance(60.0)  # past the flap window: the trend is real now
        d = ctl.tick()
        assert d["action"] == "scale_in" and d["outcome"] is None
        assert r.calls == ["scale_out", "scale_in"]
    finally:
        ctl.close()


def test_rebalance_targets_the_hot_spot():
    r = FakeRouter(n=3, burn=0.5, loads=[20, 1, 1])
    clk = FakeClock()
    ctl = _ctl(r, clk, hysteresis=2, shed_frac=0.5)
    try:
        d = ctl.tick()
        assert d["action"] is None  # imbalance streak 1 < hysteresis
        clk.advance(1.0)
        d = ctl.tick()
        assert d["action"] == "rebalance" and d["replica"] == 0
        assert r.calls == ["rebalance"]
        assert r.weights[0] == pytest.approx(0.5)
    finally:
        ctl.close()


def test_balanced_or_trivial_load_never_rebalances():
    clk = FakeClock()
    r = FakeRouter(n=3, burn=0.5, loads=[3, 0, 0])  # top below the floor
    ctl = _ctl(r, clk, hysteresis=1)
    try:
        assert ctl.tick()["reason"] == "steady"
        assert r.calls == []
    finally:
        ctl.close()


def test_action_error_degrades_to_noop_and_retries():
    r = FakeRouter(n=1, burn=5.0)
    r.fail = RuntimeError("spawn exploded")
    clk = FakeClock()
    ctl = _ctl(r, clk, hysteresis=1)
    try:
        d = ctl.tick()
        assert d["action"] == "scale_out"
        assert r.n == 1  # nothing changed
        assert ctl._last_action["outcome"] == "error"
        assert "spawn exploded" in ctl._last_action["error"]
        r.fail = None
        clk.advance(1.0)
        assert ctl.tick()["action"] == "scale_out"
        assert r.n == 2
    finally:
        ctl.close()


def test_single_flight_busy_then_timeout_accounting():
    """A second decision while an action runs is a no-op ('busy'); past
    action_timeout_s the in-flight action is declared timed out (the
    controller degrades to doing nothing) and its eventual completion is
    recorded as outcome='timeout', after which control resumes."""
    r = FakeRouter(n=1, burn=5.0)
    release = threading.Event()

    def slow_add():
        release.wait(10.0)
        r.n += 1
        return r.n - 1

    r.add_replica = slow_add
    clk = FakeClock()
    ctl = _ctl(r, clk, hysteresis=1, threaded=True, action_timeout_s=5.0)
    try:
        d = ctl.tick()
        assert d["action"] == "scale_out"
        clk.advance(1.0)
        d = ctl.tick()
        assert d["action"] is None and d["reason"] == "busy"
        clk.advance(10.0)  # past the timeout while still in flight
        d = ctl.tick()
        assert d["reason"] == "busy"
        assert ctl._action is not None and ctl._action["timed_out"]
        release.set()
        deadline = time.monotonic() + 5.0
        while ctl._action is not None and time.monotonic() < deadline:
            time.sleep(0.01)
        assert ctl._action is None
        assert ctl._last_action["outcome"] == "timeout"
        clk.advance(1.0)
        d = ctl.tick()  # single-flight slot is free again
        assert d["action"] == "scale_out"
    finally:
        release.set()
        ctl.close()


def test_no_slo_means_no_scale_out():
    """A burn-less fleet (no SLOs configured) never scales out and reads
    as permanent slack — min_replicas floors the shrink."""
    r = FakeRouter(n=2, burn=0.0)
    r._fleet_slo = lambda: None
    clk = FakeClock()
    ctl = _ctl(r, clk, hysteresis=1, min_replicas=1)
    try:
        d = ctl.tick()
        assert d["action"] == "scale_in"
        assert r.n == 1
        clk.advance(1.0)
        assert ctl.tick()["reason"] == "at-min"
    finally:
        ctl.close()


def test_payload_and_debug_fleet_endpoint():
    """payload() exposes bounds/burn/streaks/history/view; the provider
    registry serves it on /debug/fleet and prunes at close."""
    r = FakeRouter(n=2, burn=0.7, loads=[1, 2])
    clk = FakeClock()
    ctl = _ctl(r, clk, hysteresis=2)
    try:
        ctl.tick()
        body = ctl.payload()
        assert body["replicas"] == 2
        assert body["bounds"] == {"min": 1, "max": 4}
        assert body["burn"] == pytest.approx(0.7)
        assert body["streaks"] == {"hot": 0, "slack": 0, "imbalance": 0}
        assert [v["replica"] for v in body["view"]] == [0, 1]
        assert body["replica_seconds"] >= 0.0
        code, payload = fleet_payload()
        assert code == 200 and payload["status"] == "ok"
        mine = [f for f in payload["fleets"]
                if f.get("controller") == ctl._name]
        assert mine and mine[0]["replicas"] == 2
    finally:
        ctl.close()
    code, payload = fleet_payload()
    assert all(f.get("controller") != ctl._name
               for f in payload["fleets"])


def test_console_renders_fleet_panel():
    """The ops console's elastic-fleet panel renders bounds/streaks and
    recent actions from the /debug/fleet payload — and old servers
    (fleet=None) render without the panel or its separator."""
    from marlin_tpu.obs import console

    r = FakeRouter(n=1, burn=5.0)
    clk = FakeClock()
    ctl = _ctl(r, clk, hysteresis=1)
    try:
        ctl.tick()
        frame = console.render({}, {"scopes": []},
                               fleet={"fleets": [ctl.payload()]})
        assert f"fleet {r._name}" in frame
        assert "replicas=2 [1..4]" in frame
        assert "scale_out  -> ok" in frame
        bare = console.render({}, {"scopes": []}, fleet=None)
        assert "fleet " not in bare and len(bare) < len(frame)
    finally:
        ctl.close()


def test_controller_emits_fleet_events():
    log = _CaptureLog()
    r = FakeRouter(n=1, burn=5.0)
    clk = FakeClock()
    ctl = FleetController(r, clock=clk, log=log, min_replicas=1,
                          max_replicas=4, eval_interval_s=1.0,
                          out_burn=1.0, in_burn=0.1, hysteresis=1,
                          cooldown_s=0.0, flap_window_s=0.0,
                          threaded=False)
    try:
        ctl.tick()
        recs = [x for x in log.records if x["kind"] == "fleet"]
        assert any(x.get("action") == "scale_out"
                   and x.get("outcome") == "ok" for x in recs)
    finally:
        ctl.close()


def test_replica_seconds_accumulate_on_the_injected_clock():
    r = FakeRouter(n=3, burn=0.5)
    clk = FakeClock()
    ctl = _ctl(r, clk, hysteresis=99)
    try:
        start = ctl.replica_seconds()
        ctl.tick()
        clk.advance(10.0)
        ctl.tick()
        assert ctl.replica_seconds() - start == pytest.approx(30.0)
    finally:
        ctl.close()


# ----------------------------------------------------------------- chaos


@pytest.mark.parametrize("leg", ["spawn-", "join-"])
def test_kill_fresh_replica_before_join(params, leg):
    """A spawn that dies before the ring join is discarded whole: the
    fleet is untouched, in-flight traffic unaffected (no work existed on
    the orphan to lose), and the next scale-out succeeds."""
    router = Router(_factory(params), replicas=1, supervise=False,
                    rng=random.Random(3))
    try:
        hs = [router.submit(Request(prompt=[1 + i % 5, 2], steps=3))
              for i in range(4)]
        with faults.injected("serve.fleet", RaiseFault(times=1, match=leg)):
            with pytest.raises(Exception):
                router.add_replica()
        assert router.replica_count() == 1
        hs += [router.submit(Request(prompt=[2, 1 + i % 5], steps=3))
               for i in range(4)]
        router.drain()
        for h in hs:
            r = h.result(timeout=120)
            assert r.status == STATUS_OK, (r.status, r.reason)
        idx = router.add_replica()  # the fault was one-shot
        assert router.replica_count() == 2 and idx >= 1
        for rep in router._replicas:
            audit = rep.engine.kvpool_audit()
            assert audit["ok"], audit["errors"]
    finally:
        router.close()


def test_kill_retire_leg_aborts_atomically(params):
    """A fault on the retire leg fires BEFORE any state moves: the
    replica returns to rotation (routable again), nothing migrated, and
    a later retire completes normally."""
    router = Router(_factory(params), replicas=2, supervise=False,
                    rng=random.Random(6))
    try:
        with faults.injected("serve.fleet",
                             RaiseFault(times=1, match="retire-")):
            with pytest.raises(Exception):
                router.retire_replica(0)
        assert router.replica_count() == 2
        assert sorted(r.idx for r in router._candidates()) == [0, 1]
        hs = [router.submit(Request(prompt=[4, 1 + i % 4], steps=3))
              for i in range(6)]
        router.drain()
        assert all(h.result(timeout=120).status == STATUS_OK for h in hs)
        assert router.retire_replica(0) == 0
        assert router.replica_count() == 1
    finally:
        router.close()


def test_kill_shed_leg_leaves_weights(params):
    router = Router(_factory(params), replicas=2, supervise=False,
                    rng=random.Random(8))
    try:
        with faults.injected("serve.fleet",
                             RaiseFault(times=1, match="shed-")):
            with pytest.raises(Exception):
                router.shed_weight(idx=0, frac=0.5)
        assert all(v["weight"] == 1.0 for v in router.replica_view())
        idx, w = router.shed_weight(idx=0, frac=0.5)
        assert idx == 0 and w == pytest.approx(0.5)
    finally:
        router.close()


def test_kill_donor_mid_scale_in_stays_lossless(params):
    """The donor dying mid-migration (export/adopt legs) during a
    scale-in degrades to the retry path: every request still reaches
    exactly one ok Result (bit-identical) and no page leaks anywhere —
    the PR 12 guarantee carried onto the retire path."""
    memledger.reset_ledger()
    for leg in ("export:", "adopt:"):
        router = Router(_factory(params, max_batch=8, queue_depth=512,
                                 num_pages=512),
                        replicas=2,
                        supervisor_kw=dict(backoff_s=0.005, poll_s=0.02),
                        rng=random.Random(11))
        try:
            # wedge both workers so the retire finds real rows to export
            with faults.injected("serve.decode_step",
                                 DelayFault(seconds=0.5, times=2)):
                hs = [router.submit(Request(prompt=[3, 1 + i % 4],
                                            steps=8))
                      for i in range(6)]
                time.sleep(0.05)  # rows live mid-decode
                with faults.injected("serve.migrate",
                                     RaiseFault(times=1, match=leg)):
                    retired = router.retire_replica(0)
            assert retired == 0 and router.replica_count() == 1
            router.drain()
            for h in hs:
                r = h.result(timeout=120)
                assert r.status == STATUS_OK, (leg, r.status, r.reason)
                assert r.tokens.tolist() == _ref(params, h.request.prompt,
                                                 8)
            for rep in router._replicas:
                audit = rep.engine.kvpool_audit()
                assert audit["ok"], (leg, audit["errors"])
            assert router.pending() == 0
            # the ledger balances after the faulted retire: the donor's
            # bytes and any in-flight migration blob were debited exactly
            # once, and only the survivor still owns device memory
            led = memledger.get_ledger()
            mem_audit = led.audit()
            assert mem_audit["ok"], (leg, mem_audit["errors"])
            assert led.totals().get("migration", 0) == 0
            live = {rep.engine._name for rep in router._replicas}
            for e in led.entries():
                if e["component"] in ("kvpool", "migration"):
                    assert e["owner"] in live, (leg, e)
        finally:
            router.close()


def test_controller_rebuild_mid_fleet_resumes_from_router(params):
    """Killing the controller loses only streak counters: a new one on
    the same router reconstructs the fleet from replica_view/count alone
    and keeps controlling correctly."""
    router = Router(_factory(params), replicas=2, supervise=False,
                    rng=random.Random(9))
    clk = FakeClock()
    ctl = _ctl(router, clk, hysteresis=1, max_replicas=3)
    try:
        ctl._burn_signal = lambda: 9.0
        assert ctl.tick()["action"] == "scale_out"
        assert router.replica_count() == 3
        ctl.close()  # controller dies; the fleet stays at its size
        assert router.replica_count() == 3
        ctl2 = _ctl(router, clk, hysteresis=1, min_replicas=1,
                    max_replicas=3)
        try:
            ctl2._burn_signal = lambda: 0.0
            clk.advance(1.0)
            d = ctl2.tick()  # re-derives scale-in purely from router truth
            assert d["action"] == "scale_in" and d["replicas"] == 3
            assert router.replica_count() == 2
        finally:
            ctl2.close()
        hs = [router.submit(Request(prompt=[2, 1 + i % 4], steps=3))
              for i in range(6)]
        router.drain()
        assert all(h.result(timeout=120).status == STATUS_OK for h in hs)
    finally:
        ctl.close()
        router.close()


@pytest.mark.slow
def test_fleet_chaos_soak(params):
    """The acceptance soak: >= 6 real scale events under ~64 req/s — out,
    in, and rebalance, each also killed once mid-event on a serve.fleet
    leg, plus a controller killed and rebuilt mid-fleet. Every request
    ever accepted reaches exactly one ok Result (bit-identical), ZERO
    restart from token 0 (the killed legs abort before any state moves),
    and every surviving pool audits clean. The burn signal is scripted
    (test_slo.py owns the SLO windows); the actions are entirely real."""
    memledger.reset_ledger()
    router = Router(_factory(params, max_batch=8, queue_depth=1024,
                             num_pages=512),
                    replicas=1,
                    supervisor_kw=dict(backoff_s=0.005, poll_s=0.02),
                    rng=random.Random(13))
    clk = FakeClock()
    burn = {"v": 0.0}

    def make_ctl():
        c = FleetController(router, clock=clk, min_replicas=1,
                            max_replicas=3, eval_interval_s=0.0,
                            out_burn=1.0, in_burn=0.1, hysteresis=1,
                            cooldown_s=0.0, flap_window_s=0.0,
                            action_timeout_s=60.0, threaded=False)
        c._burn_signal = lambda: burn["v"]
        return c

    ctl = make_ctl()
    handles, lock = [], threading.Lock()
    stop = threading.Event()

    def pump():
        i = 0
        while not stop.is_set():
            h = router.submit(Request(prompt=[1 + i % 8, 3, 2],
                                      steps=2 + i % 6, max_attempts=3))
            with lock:
                handles.append(h)
            i += 1
            time.sleep(0.015)  # 2 pumps x ~32 req/s

    def step(burn_v, fault_leg=None):
        time.sleep(0.25)  # dwell: traffic keeps flowing between events
        burn["v"] = burn_v
        clk.advance(1.0)
        if fault_leg is None:
            return ctl.tick()
        with faults.injected("serve.fleet",
                             RaiseFault(times=1, match=fault_leg)):
            return ctl.tick()

    threads = [threading.Thread(target=pump) for _ in range(2)]
    try:
        for t in threads:
            t.start()
        time.sleep(0.1)
        # 1. scale-out killed before the ring join -> degraded to no-op
        d = step(9.0, fault_leg="join-")
        assert d["action"] == "scale_out"
        assert router.replica_count() == 1
        assert ctl._last_action["outcome"] == "error"
        # 2-3. scale out for real, 1 -> 3
        assert step(9.0)["action"] == "scale_out"
        assert step(9.0)["action"] == "scale_out"
        assert router.replica_count() == 3
        time.sleep(0.2)  # let traffic spread across the grown fleet
        # 4. rebalance killed mid-shed -> weights untouched
        hot = router._replicas[0].idx
        ctl._hot_spot = lambda view: hot
        d = step(0.5, fault_leg="shed-")
        assert d["action"] == "rebalance"
        assert ctl._last_action["outcome"] == "error"
        assert all(v["weight"] == 1.0 for v in router.replica_view())
        # 5. rebalance for real
        assert step(0.5)["action"] == "rebalance"
        assert any(v["weight"] < 1.0 for v in router.replica_view())
        ctl._hot_spot = lambda view: None
        # 6. scale-in killed on the retire leg -> fleet unchanged
        d = step(0.0, fault_leg="retire-")
        assert d["action"] == "scale_in"
        assert ctl._last_action["outcome"] == "error"
        assert router.replica_count() == 3
        # 7-8. scale in for real, 3 -> 1: live rows migrate losslessly
        assert step(0.0)["action"] == "scale_in"
        assert step(0.0)["action"] == "scale_in"
        assert router.replica_count() == 1
        # 9. controller killed and rebuilt mid-fleet, still under load
        ctl.close()
        ctl = make_ctl()
        d = step(9.0)
        assert d["action"] == "scale_out"
        assert router.replica_count() == 2
        stop.set()
        for t in threads:
            t.join()
        router.drain()
        results = [h.result(timeout=180) for h in handles]
        assert len(results) > 100
        bad = [(r.status, r.reason) for r in results
               if r.status != STATUS_OK]
        assert not bad, bad[:5]  # zero dropped or double-terminal
        for h, r in zip(handles, results):
            assert r.tokens.tolist() == _ref(params, h.request.prompt,
                                             h.request.steps)
        snap = router.snapshot()
        assert snap["retries"] == 0  # zero token-0 restarts, all events
        ok_events = [x for x in ctl.payload()["history"]
                     if x["outcome"] == "ok"]
        assert len(ok_events) >= 1  # the rebuilt controller's own event
        for rep in router._replicas:
            audit = rep.engine.kvpool_audit()
            assert audit["ok"], audit["errors"]
        # nine scale events later the ledger still balances exactly: no
        # retired replica or aborted scale event left bytes behind
        led = memledger.get_ledger()
        mem_audit = led.audit()
        assert mem_audit["ok"], mem_audit["errors"]
        assert led.totals().get("migration", 0) == 0
        live = {rep.engine._name for rep in router._replicas}
        for e in led.entries():
            if e["component"] in ("kvpool", "migration"):
                assert e["owner"] in live, e
    finally:
        stop.set()
        ctl.close()
        router.close()
