"""Chaos suite: the recovery subsystem exercised against injected failures.

The reference never tests its fault tolerance (Spark lineage is assumed to
work, SURVEY.md §5.3); here every recovery guarantee is *demonstrated* under
deterministic fault injection (marlin_tpu.utils.faults):

- a checkpoint torn mid-write is never visible to a reader (atomic commit),
- a committed-but-corrupt generation fails CRC verification and recovery
  falls back to the previous generation,
- a flaky remote filesystem succeeds through RetryPolicy with backoff, with
  the retries visible in the EventLog,
- NaN metrics and step crashes restart from the last good checkpoint with
  exactly one metric entry per step,
- heartbeat distinguishes erroring devices from wedged ones.

Everything runs on the 8-device CPU mesh (JAX_PLATFORMS=cpu); long soak
scenarios are marked `slow` and stay out of tier-1.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from marlin_tpu.io.checkpoint import (
    CheckpointCorruptError,
    list_generations,
    load_checkpoint,
    load_sharded,
    prune_generations,
    save_checkpoint,
    save_sharded,
    verify_generation,
)
from marlin_tpu.io.fs import open_path
from marlin_tpu.utils import faults
from marlin_tpu.utils.failure import ResilientLoop, heartbeat
from marlin_tpu.utils.faults import (
    DelayFault,
    FaultInjected,
    MutateFault,
    RaiseFault,
    Schedule,
    TornWriteFault,
)
from marlin_tpu.utils.retry import RetryPolicy, set_retry_policy
from marlin_tpu.utils.tracing import EventLog


# ---------------------------------------------------------------- helpers

def _step_fn(state, i):
    """Deterministic contraction toward 1.0; loss is comparable across
    replays to fp exactness, so resumed trajectories equal uninterrupted
    ones."""
    w = state["w"] - 0.25 * (state["w"] - 1.0)
    return {"w": w}, float(jnp.sum((w - 1.0) ** 2))


def _oracle(iterations):
    w = np.zeros((4,), np.float32)
    out = []
    for _ in range(iterations):
        w = w - 0.25 * (w - 1.0)
        out.append(float(np.sum((w - 1.0) ** 2)))
    return out


def _flip_byte(path, offset=-20):
    """Corrupt one byte in place — a bit-rot / partial-overwrite stand-in."""
    with open(path, "r+b") as f:
        f.seek(offset, os.SEEK_END)
        b = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([b[0] ^ 0xFF]))


def _fast_policy(**kw):
    """A RetryPolicy that never really sleeps (chaos tests stay fast)."""
    kw.setdefault("sleep", lambda s: None)
    kw.setdefault("seed", 0)
    return RetryPolicy(**kw)


# ------------------------------------------------------- harness mechanics

def test_fault_registry_basics():
    with pytest.raises(ValueError, match="unknown fault point"):
        faults.inject("no.such.point", RaiseFault())
    f = faults.inject("step.run", RaiseFault(times=1))
    assert faults.active() == {"step.run": [f]}
    with pytest.raises(FaultInjected):
        faults.fire("step.run", step=0)
    # exhausted faults auto-deregister: nothing leaks into the next test
    assert faults.active() == {}
    faults.fire("step.run", step=1)  # no-op


def test_injected_context_manager_removes_unfired():
    with faults.injected("fs.open", RaiseFault(times=5)) as f:
        assert faults.active()
        with pytest.raises(FaultInjected):
            faults.fire("fs.open", path="x")
    assert faults.active() == {}
    assert f.fired == 1


def test_fault_match_gates_on_path():
    with faults.injected("fs.open", RaiseFault(times=-1, match="target")):
        faults.fire("fs.open", path="/elsewhere/file")  # no match, no fire
        with pytest.raises(FaultInjected):
            faults.fire("fs.open", path="/data/target/file")


def test_schedule_seeded_reproducible():
    a = Schedule(seed=123, rate=0.4)
    b = Schedule(seed=123, rate=0.4)
    pat_a = [a.should_fire() for _ in range(50)]
    pat_b = [b.should_fire() for _ in range(50)]
    assert pat_a == pat_b, "same seed must give the identical chaos schedule"
    assert any(pat_a) and not all(pat_a)
    c = Schedule(fire_on=[0, 3])
    assert [c.should_fire() for _ in range(5)] == [True, False, False, True,
                                                  False]


def test_torn_write_truncates_and_flushes(tmp_path):
    p = str(tmp_path / "torn.bin")
    with faults.injected("fs.open",
                         TornWriteFault(keep_bytes=10, then_raise=True)):
        with pytest.raises(FaultInjected, match="torn write"):
            with open_path(p, "wb") as f:
                f.write(b"x" * 64)
    assert os.path.getsize(p) == 10, "the durable prefix of a torn write"


# ------------------------------------------------------------ retry policy

def test_retry_backoff_grows_and_caps():
    pol = RetryPolicy(max_attempts=6, base_delay=0.1, multiplier=2.0,
                      max_delay=0.5, jitter=0.0, sleep=lambda s: None)
    ds = list(pol.delays())
    assert ds == [0.1, 0.2, 0.4, 0.5, 0.5]


def test_retry_jitter_seeded_reproducible():
    d1 = list(RetryPolicy(max_attempts=5, jitter=0.25, seed=9,
                          sleep=lambda s: None).delays())
    d2 = list(RetryPolicy(max_attempts=5, jitter=0.25, seed=9,
                          sleep=lambda s: None).delays())
    assert d1 == d2
    base = list(RetryPolicy(max_attempts=5, jitter=0.0,
                            sleep=lambda s: None).delays())
    assert all(j >= b for j, b in zip(d1, base)), "jitter only stretches"


def test_retry_succeeds_after_transient_failures():
    sleeps = []
    pol = _fast_policy(max_attempts=4, base_delay=0.01, jitter=0.0,
                       sleep=sleeps.append)
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] <= 2:
            raise OSError("transient")
        return "ok"

    assert pol.call(flaky, describe="unit") == "ok"
    assert calls["n"] == 3 and pol.retries == 2
    assert sleeps == [0.01, 0.02], "exponential backoff between attempts"


def test_retry_exhausts_and_reraises():
    pol = _fast_policy(max_attempts=3)
    with pytest.raises(OSError, match="always"):
        pol.call(lambda: (_ for _ in ()).throw(OSError("always")))


def test_retry_deadline_with_injected_clock():
    t = {"now": 0.0}

    def clock():
        return t["now"]

    def sleep(s):
        t["now"] += s

    pol = RetryPolicy(max_attempts=100, base_delay=1.0, multiplier=1.0,
                      jitter=0.0, deadline=3.5, clock=clock, sleep=sleep)
    calls = {"n": 0}

    def failing():
        calls["n"] += 1
        raise OSError("down")

    with pytest.raises(OSError):
        pol.call(failing)
    # budget 3.5s at 1s per backoff: attempts at t=0,1,2,3 then the next
    # backoff would cross the deadline — fail fast instead of attempt 100
    assert calls["n"] == 4


def test_flaky_remote_fs_retries_visible_in_event_log(tmp_path):
    """Acceptance: a remote fs raising on the first 2 of 3 open calls
    succeeds through RetryPolicy, and the retries land in the EventLog."""
    fsspec = pytest.importorskip("fsspec")
    memfs = fsspec.filesystem("memory")
    with memfs.open("/flaky_suite/data.txt", "w") as f:
        f.write("payload")
    log = EventLog(str(tmp_path / "events.jsonl"))
    prev = set_retry_policy(_fast_policy(max_attempts=4, base_delay=0.01,
                                         event_log=log))
    try:
        with faults.injected(
                "fs.open",
                RaiseFault(OSError("flaky object store"), times=2,
                           match="flaky_suite")) as fault:
            with open_path("memory://flaky_suite/data.txt") as f:
                assert f.read() == "payload"
        assert fault.fired == 2, "first two opens failed, third succeeded"
    finally:
        set_retry_policy(prev)
    retries = [e for e in log.read() if e["kind"] == "retry"]
    assert [e["attempt"] for e in retries] == [1, 2]
    assert all("flaky_suite" in e["op"] for e in retries)
    assert retries[1]["delay_s"] > retries[0]["delay_s"] > 0


def test_flaky_remote_listing_retries(tmp_path):
    fsspec = pytest.importorskip("fsspec")
    memfs = fsspec.filesystem("memory")
    memfs.makedirs("/flaky_ls", exist_ok=True)
    with memfs.open("/flaky_ls/a", "w") as f:
        f.write("x")
    from marlin_tpu.io.fs import list_names
    prev = set_retry_policy(_fast_policy(max_attempts=3))
    try:
        with faults.injected("fs.list",
                             RaiseFault(OSError("hiccup"), times=1,
                                        match="flaky_ls")):
            assert "a" in list_names("memory://flaky_ls")
    finally:
        set_retry_policy(prev)


# ------------------------------------------------- crash-safe checkpointing

def test_torn_checkpoint_never_committed(tmp_path):
    d = str(tmp_path)
    state = {"w": jnp.arange(8.0)}
    with faults.injected("fs.open",
                         TornWriteFault(keep_bytes=32, match="state.npz")):
        with pytest.raises(FaultInjected):
            save_checkpoint(state, d, step=1)
    assert list_generations(d) == [], "a torn generation must be invisible"
    with pytest.raises(FileNotFoundError):
        load_checkpoint(state, d)


def test_silent_truncation_caught_by_crc(tmp_path):
    """A torn write that doesn't raise (power loss after a partial flush):
    the generation commits, but CRC verification refuses it."""
    d = str(tmp_path)
    state = {"w": jnp.arange(8.0)}
    with faults.injected(
            "fs.open",
            TornWriteFault(keep_bytes=64, then_raise=False,
                           match="state.npz")):
        save_checkpoint(state, d, step=1)
    assert list_generations(d) == [1], "committed — the tear was silent"
    with pytest.raises(CheckpointCorruptError, match="checksum mismatch"):
        load_checkpoint(state, d)
    with pytest.raises(CheckpointCorruptError):
        verify_generation(d, 1)


def test_remote_torn_checkpoint_marker_protocol():
    """Remote paths have no atomic rename: the COMMITTED marker alone is the
    commit, so a save killed before the marker leaves nothing loadable."""
    pytest.importorskip("fsspec")
    d = "memory://chaos_remote_torn/ck"
    state = {"w": jnp.arange(4.0)}
    with faults.injected("fs.open",
                         TornWriteFault(keep_bytes=16, match="state.npz")):
        with pytest.raises(FaultInjected):
            save_checkpoint(state, d, step=2)
    assert list_generations(d) == []
    save_checkpoint(state, d, step=2)  # clean retry of the same step
    restored, step = load_checkpoint(state, d)
    assert step == 2
    np.testing.assert_array_equal(np.asarray(restored["w"]), np.arange(4.0))


def test_crash_before_manifest_not_committed(tmp_path):
    d = str(tmp_path)
    with faults.injected("ckpt.manifest", RaiseFault(OSError("died"))):
        with pytest.raises(OSError):
            save_checkpoint({"w": jnp.ones(3)}, d, step=5)
    assert list_generations(d) == []


def test_crash_at_payload_write_not_committed(tmp_path):
    """A failure at the ``ckpt.write`` point (the payload write itself, before
    any bytes land) leaves the previous generation untouched and loadable,
    and a clean retry of the failed step commits normally."""
    d = str(tmp_path)
    state = {"w": jnp.arange(6.0)}
    save_checkpoint(state, d, step=1)
    with faults.injected("ckpt.write", RaiseFault(OSError("disk full"))):
        with pytest.raises(OSError):
            save_checkpoint({"w": jnp.zeros(6)}, d, step=2)
    assert list_generations(d) == [1], "the failed save must not commit"
    restored, step = load_checkpoint(state, d)
    assert step == 1
    np.testing.assert_array_equal(np.asarray(restored["w"]), np.arange(6.0))
    save_checkpoint({"w": jnp.zeros(6)}, d, step=2)  # clean retry
    assert list_generations(d) == [1, 2]


def test_resilient_loop_falls_back_past_corrupt_latest(tmp_path):
    """Acceptance: corrupt latest generation -> ResilientLoop resumes from
    the previous committed one and completes with one metric per step."""
    d = str(tmp_path)
    loop = ResilientLoop(_step_fn, d, checkpoint_every=2)
    loop.run({"w": jnp.zeros((4,), jnp.float32)}, 4)
    assert list_generations(d) == [2, 4]
    _flip_byte(str(tmp_path / "ckpt_00000004" / "state.npz"))

    log = EventLog(str(tmp_path / "events.jsonl"))
    loop2 = ResilientLoop(_step_fn, d, checkpoint_every=2, event_log=log)
    state, metrics = loop2.run({"w": jnp.zeros((4,), jnp.float32)}, 10)
    assert len(metrics) == 8, "resumed at step 2 (gen 4 is corrupt)"
    np.testing.assert_allclose(metrics, _oracle(10)[2:], rtol=1e-5)
    events = [e["kind"] for e in log.read()]
    assert "resume_skip" in events and "resume" in events
    skip = next(e for e in log.read() if e["kind"] == "resume_skip")
    assert skip["step"] == 4 and "checksum" in skip["error"]


def test_kill_mid_save_then_process_restart(tmp_path):
    """Acceptance: a save killed mid-write (torn staging dir) is never
    loaded; a fresh process resumes from the previous committed generation."""
    d = str(tmp_path)
    loop1 = ResilientLoop(_step_fn, d, checkpoint_every=2, max_retries=0)
    with faults.injected(
            "fs.open",
            TornWriteFault(keep_bytes=32, match="ckpt_00000004.tmp")):
        with pytest.raises(FaultInjected):
            loop1.run({"w": jnp.zeros((4,), jnp.float32)}, 4)
    assert list_generations(d) == [2], "the torn gen 4 never committed"

    loop2 = ResilientLoop(_step_fn, d, checkpoint_every=2)
    state, metrics = loop2.run({"w": jnp.zeros((4,), jnp.float32)}, 10)
    assert len(metrics) == 8
    np.testing.assert_allclose(metrics, _oracle(10)[2:], rtol=1e-5)
    # the retried save of step 4 committed, then retention (keep=3) rolled
    # the window forward as the run progressed
    assert list_generations(d) == [6, 8, 10]


def test_resilient_loop_survives_transient_save_failure(tmp_path):
    """A save failure mid-run counts as a failure like any other: resume,
    replay, save again — the run completes without operator involvement."""
    d = str(tmp_path)
    loop = ResilientLoop(_step_fn, d, checkpoint_every=2)
    with faults.injected(
            "fs.open",
            TornWriteFault(keep_bytes=32, match="ckpt_00000004.tmp")):
        state, metrics = loop.run({"w": jnp.zeros((4,), jnp.float32)}, 6)
    assert loop.retries == 1
    assert len(metrics) == 6, "replayed steps keep one metric entry per step"
    np.testing.assert_allclose(metrics, _oracle(6), rtol=1e-5)
    assert list_generations(d)[-1] == 6


def test_corrupt_integrity_manifest_falls_back(tmp_path):
    """Satellite: a corrupt JSON manifest used to raise JSONDecodeError past
    the old (FileNotFoundError, OSError) recovery filter."""
    d = str(tmp_path)
    loop = ResilientLoop(_step_fn, d, checkpoint_every=2)
    loop.run({"w": jnp.zeros((4,), jnp.float32)}, 4)
    with open(str(tmp_path / "ckpt_00000004" / "integrity_0.json"), "w") as f:
        f.write("{ this is not json")
    loop2 = ResilientLoop(_step_fn, d, checkpoint_every=2)
    _, metrics = loop2.run({"w": jnp.zeros((4,), jnp.float32)}, 6)
    assert len(metrics) == 4, "fell back to gen 2, replayed 2..6"


def test_legacy_truncated_npz_falls_back(tmp_path):
    """Satellite: a truncated legacy .npz raises ValueError/BadZipFile —
    recovery must walk back to the older legacy generation, not crash."""
    d = str(tmp_path)
    w = np.full((4,), 0.4375, np.float32)  # the step-2 oracle state
    np.savez(str(tmp_path / "ckpt_00000002.npz"), leaf_0=w)
    with open(str(tmp_path / "ckpt_00000004.npz"), "wb") as f:
        f.write(b"PK\x03\x04 truncated garbage")
    with open(str(tmp_path / "latest"), "w") as f:
        f.write("4")
    loop = ResilientLoop(_step_fn, d, checkpoint_every=100)
    state, metrics = loop.run({"w": jnp.zeros((4,), jnp.float32)}, 6)
    assert len(metrics) == 4, "resumed from the intact legacy gen 2"
    np.testing.assert_allclose(metrics, _oracle(6)[2:], rtol=1e-5)


def test_all_generations_corrupt_warns_and_restarts(tmp_path):
    """When checkpoints exist but NONE restores, the restart-from-scratch is
    announced with a RuntimeWarning naming every skipped generation — a
    config mismatch must never be silently absorbed as a fresh start."""
    d = str(tmp_path)
    loop = ResilientLoop(_step_fn, d, checkpoint_every=2)
    loop.run({"w": jnp.zeros((4,), jnp.float32)}, 4)
    for gen in (2, 4):
        _flip_byte(str(tmp_path / f"ckpt_{gen:08d}" / "state.npz"))
    loop2 = ResilientLoop(_step_fn, d, checkpoint_every=100)
    with pytest.warns(RuntimeWarning, match="no generation .* was restorable"):
        state, metrics = loop2.run({"w": jnp.zeros((4,), jnp.float32)}, 4)
    assert len(metrics) == 4, "fresh restart still completes the run"
    np.testing.assert_allclose(metrics, _oracle(4), rtol=1e-5)


def test_verify_generation_legacy_npz_passes(tmp_path):
    np.savez(str(tmp_path / "ckpt_00000003.npz"), leaf_0=np.zeros(2))
    assert list_generations(str(tmp_path)) == [3]
    verify_generation(str(tmp_path), 3)  # vacuous: no integrity data
    with pytest.raises(CheckpointCorruptError):
        verify_generation(str(tmp_path), 9)  # absent step still errors


def test_retention_keeps_last_k(tmp_path):
    d = str(tmp_path)
    loop = ResilientLoop(_step_fn, d, checkpoint_every=2, keep=2)
    loop.run({"w": jnp.zeros((4,), jnp.float32)}, 12)
    assert list_generations(d) == [10, 12]
    assert not (tmp_path / "ckpt_00000002").exists()
    # pruning never touches the newest generations
    restored, step = load_checkpoint({"w": jnp.zeros((4,), jnp.float32)}, d)
    assert step == 12


def test_prune_generations_direct(tmp_path):
    d = str(tmp_path)
    state = {"w": jnp.ones((2,))}
    for s in (1, 2, 3, 4):
        save_checkpoint(state, d, step=s)
    assert list_generations(d) == [1, 2, 3, 4]
    # torn debris older than the newest commit is reclaimed too: a marker-less
    # generation dir and an orphaned staging dir (what crashes leave behind)
    os.makedirs(str(tmp_path / "ckpt_00000002" / "junk"), exist_ok=True)
    os.remove(str(tmp_path / "ckpt_00000002" / "COMMITTED"))
    os.makedirs(str(tmp_path / "ckpt_00000003.tmp"), exist_ok=True)
    assert prune_generations(d, keep=2) == [1]
    assert list_generations(d) == [3, 4]
    assert not (tmp_path / "ckpt_00000002").exists()
    assert not (tmp_path / "ckpt_00000003.tmp").exists()
    import marlin_tpu as mt
    with mt.config_context(ckpt_keep=1):
        save_checkpoint(state, d, step=5)  # keep=None defers to config
    assert list_generations(d) == [5]


def test_stale_shards_cleared_on_resave(tmp_path, mesh):
    """Satellite: re-saving under a different sharding/process count must not
    leave old shard_*/manifest_* files for _read_manifests to mix in."""
    import marlin_tpu as mt
    from jax.sharding import NamedSharding, PartitionSpec as P

    d = str(tmp_path / "arr")
    a = mt.BlockMatrix.random(0, 40, 24, mesh=mesh)  # 2x4 mesh
    save_sharded(a.data, d)
    # simulate the leftover manifest of a second process from an older run
    os.rename(os.path.join(d, "manifest_0.json"),
              os.path.join(d, "manifest_1.json"))
    old_files = set(os.listdir(d))

    small = mt.create_mesh((4, 2), devices=jax.devices())
    b = mt.BlockMatrix.random(1, 40, 24, mesh=small)
    save_sharded(b.data, d)
    names = set(os.listdir(d))
    assert "manifest_1.json" not in names, "stale manifest survived"
    man = json.load(open(os.path.join(d, "manifest_0.json")))
    assert {n for n in names if n.startswith("shard_")} == \
        {sh["file"] for sh in man["shards"]}, \
        "only the new save's shard files may remain"
    back = load_sharded(d, sharding=NamedSharding(small, P("rows", "cols")))
    np.testing.assert_array_equal(np.asarray(back), np.asarray(b.data))


def test_sharded_manifest_records_crc(tmp_path, mesh):
    import marlin_tpu as mt

    d = str(tmp_path / "arr")
    a = mt.BlockMatrix.random(2, 16, 8, mesh=mesh)
    integ = save_sharded(a.data, d)
    man = json.load(open(os.path.join(d, "manifest_0.json")))
    for sh in man["shards"]:
        assert sh["crc32"] == integ[sh["file"]]["crc32"]
        import zlib
        crc = zlib.crc32(open(os.path.join(d, sh["file"]), "rb").read())
        assert (crc & 0xFFFFFFFF) == sh["crc32"]


# ------------------------------------------------------- step-level faults

def test_nan_metric_recovers_from_checkpoint(tmp_path):
    """Acceptance: NaN injected into a step's metric triggers
    NonFiniteLossError recovery; the finished history has one finite entry
    per step."""
    d = str(tmp_path)
    with faults.injected("step.run", MutateFault(float("nan"), times=1)):
        loop = ResilientLoop(_step_fn, d, checkpoint_every=2)
        state, metrics = loop.run({"w": jnp.zeros((4,), jnp.float32)}, 6)
    assert loop.retries == 1
    assert len(metrics) == 6
    assert all(np.isfinite(metrics))
    np.testing.assert_allclose(metrics, _oracle(6), rtol=1e-5)


def test_step_crash_recovers_from_checkpoint(tmp_path):
    d = str(tmp_path)
    fault = RaiseFault(RuntimeError("device lost mid-step"),
                       schedule=Schedule(fire_on=[3]), times=1)
    with faults.injected("step.run", fault):
        loop = ResilientLoop(_step_fn, d, checkpoint_every=2)
        state, metrics = loop.run({"w": jnp.zeros((4,), jnp.float32)}, 6)
    assert fault.fired == 1 and loop.retries == 1
    assert len(metrics) == 6
    np.testing.assert_allclose(metrics, _oracle(6), rtol=1e-5)


def test_retry_budget_exhaustion_reraises(tmp_path):
    d = str(tmp_path)
    with faults.injected("step.run",
                         RaiseFault(RuntimeError("hard failure"), times=-1)):
        loop = ResilientLoop(_step_fn, d, checkpoint_every=2, max_retries=2)
        with pytest.raises(RuntimeError, match="hard failure"):
            loop.run({"w": jnp.zeros((4,), jnp.float32)}, 6)
        assert loop.retries == 3


# ------------------------------------------------------- heartbeat failure

def test_heartbeat_device_error_recorded():
    """Satellite: a probe that ERRORS (dead device) lands in .errors with
    latency inf; the healthy devices still report."""
    dev0 = str(jax.devices()[0])
    with faults.injected("device.probe",
                         RaiseFault(RuntimeError("device gone"),
                                    match=dev0)):
        out = heartbeat(timeout_s=10.0, raise_on_failure=False)
    assert out[dev0] == float("inf")
    assert dev0 in out.errors and "device gone" in str(out.errors[dev0])
    healthy = [v for k, v in out.items() if k != dev0]
    assert len(healthy) == len(jax.devices()) - 1
    assert all(v < 10.0 for v in healthy)


def test_heartbeat_raise_on_failure_payload():
    dev0 = str(jax.devices()[0])
    with faults.injected("device.probe",
                         RaiseFault(RuntimeError("bus error"), match=dev0)):
        with pytest.raises(TimeoutError) as ei:
            heartbeat(timeout_s=10.0, raise_on_failure=True)
    err = ei.value
    assert dev0 in str(err) and "bus error" in str(err)
    assert err.results[dev0] == float("inf"), ".results rides on the error"
    assert dev0 in err.results.errors


def test_heartbeat_timeout_vs_error():
    """A wedged (slow) device times out WITHOUT an .errors entry — the
    distinction operators triage on."""
    dev0 = str(jax.devices()[0])
    with faults.injected("device.probe", DelayFault(2.0, match=dev0)):
        out = heartbeat(timeout_s=0.25, raise_on_failure=False)
    assert out[dev0] == float("inf")
    assert dev0 not in out.errors, "slow is a timeout, not a device error"


def test_heartbeat_happy_path_all_finite():
    out = heartbeat(timeout_s=30.0)
    assert set(out) == {str(d) for d in jax.devices()}
    assert all(np.isfinite(v) for v in out.values())
    assert out.errors == {}


# ------------------------------------------------------------- slow chaos

@pytest.mark.slow
def test_soak_seeded_flaky_fs_full_run(tmp_path):
    """Soak: a probabilistically flaky filesystem (seeded schedule, so the
    chaos is reproducible) under a long checkpointed run — retries absorb
    every transient, the trajectory matches the uninterrupted oracle."""
    d = str(tmp_path)
    prev = set_retry_policy(_fast_policy(max_attempts=6, base_delay=0.001))
    try:
        with faults.injected(
                "step.run",
                MutateFault(float("nan"), times=2,
                            schedule=Schedule(seed=7, rate=0.05))), \
             faults.injected(
                "fs.open",
                RaiseFault(OSError("flaky disk"), times=-1, match=d,
                           schedule=Schedule(seed=11, rate=0.06))):
            loop = ResilientLoop(_step_fn, d, checkpoint_every=5,
                                 max_retries=10, keep=2)
            state, metrics = loop.run({"w": jnp.zeros((4,), jnp.float32)}, 40)
    finally:
        set_retry_policy(prev)
    assert len(metrics) == 40
    np.testing.assert_allclose(metrics, _oracle(40), rtol=1e-5)
    assert len(list_generations(d)) <= 2


@pytest.mark.slow
def test_soak_repeated_corruption_always_recovers(tmp_path):
    """Soak: corrupt the newest generation between every run; each restart
    still converges on the oracle trajectory from the newest intact one."""
    d = str(tmp_path)
    loop = ResilientLoop(_step_fn, d, checkpoint_every=2, keep=4)
    loop.run({"w": jnp.zeros((4,), jnp.float32)}, 4)
    for target in (8, 12, 16):
        newest = list_generations(d)[-1]
        _flip_byte(str(tmp_path / f"ckpt_{newest:08d}" / "state.npz"))
        loop = ResilientLoop(_step_fn, d, checkpoint_every=2, keep=4)
        state, metrics = loop.run({"w": jnp.zeros((4,), jnp.float32)},
                                  target)
        start = target - len(metrics)
        np.testing.assert_allclose(metrics, _oracle(target)[start:],
                                   rtol=1e-5)
