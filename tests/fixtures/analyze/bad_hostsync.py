"""Seeded violations for host-sync: device->host pulls inside a hot
(decode/step-named) function."""

import jax
import numpy as np


def decode_step(tokens, state):
    val = tokens.item()  # scalar pull: finding
    host = np.asarray(state)  # device transfer: finding
    jax.block_until_ready(state)  # pipeline stall: finding
    return val, host


def helper(x):
    return float(x[0])  # not a hot function: no finding
