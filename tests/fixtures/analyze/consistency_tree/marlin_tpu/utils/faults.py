"""Fixture fault registry: ``net.flaky`` is seeded as both undocumented
(no docs/robustness.md row) and untested (no test literal names it)."""

KNOWN_POINTS = frozenset({
    "ckpt.write",
    "serve.step",
    "net.flaky",
})
