"""Fixture metric registrations: ``marlin_mini_depth`` is seeded as
registered-but-undocumented."""


def register(reg):
    c = reg.counter("marlin_mini_ops_total", "ops completed")
    g = reg.gauge("marlin_mini_depth", "queue depth")
    h = reg.histogram("marlin_mini_latency_seconds", "op latency")
    return c, g, h
