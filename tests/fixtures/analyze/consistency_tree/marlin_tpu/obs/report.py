"""Fixture post-mortem vocabulary: ``stale_ev`` is declared but never
emitted (stale direction); the emitters add ``mystery``/``surprise``
(unknown directions)."""

KNOWN_KINDS = frozenset({"step", "serve"})

KNOWN_SERVE_EVS = frozenset({"enqueue", "result", "stale_ev"})
