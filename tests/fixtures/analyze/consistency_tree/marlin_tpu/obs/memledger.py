"""HBM-ledger registry (fixture). Drift is planted on purpose:
``mystery_comp`` has no row in the doc's memory-attribution table, and
the doc table carries a ghost ``phantom_comp`` row."""

KNOWN_COMPONENTS = ("kvpool", "mystery_comp", "program")
