"""Fixture knob consumer: reads the live knobs, plus one typo'd read
(``cfg.pagelen``) seeded for the unknown-read direction."""

from .config import get_config


def configure(x):
    cfg = get_config()
    chunk = cfg.chunk_bytes
    retries = cfg.retry_max
    plen = cfg.pagelen  # typo: MiniConfig defines page_len
    return x, chunk, retries, plen


def legacy(x):
    cfg = get_config()
    return x, cfg.legacy_retries, cfg.page_len
