"""Fixture event emitters: one unknown EventLog kind (``mystery``) and
one unknown serve ev (``surprise``)."""


def _emit(log, **fields):
    log.event("serve", **fields)


def body(log):
    log.event("step", t=1.0)
    log.event("mystery", t=2.0)  # not in KNOWN_KINDS
    _emit(log, ev="enqueue")
    _emit(log, ev="result")
    _emit(log, ev="surprise")  # not in KNOWN_SERVE_EVS
