"""Fixture config: seeded default drift (retry_max), a dead knob
(unused_knob, also undocumented), and a DEPRECATED-exempt field."""

import dataclasses


@dataclasses.dataclass
class MiniConfig:
    chunk_bytes: int = 64 << 20
    retry_max: int = 5
    # DEPRECATED (PR 9): replaced by retry_max; parses, changes nothing
    legacy_retries: int = 2
    unused_knob: bool = False
    page_len: int = 16


_config = MiniConfig()


def get_config() -> MiniConfig:
    return _config
