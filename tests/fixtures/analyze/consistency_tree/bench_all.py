"""Fixture bench scrape acceptance list: ``marlin_mini_missing_total``
is seeded as wanted-but-never-registered."""

want = (
    "marlin_mini_ops_total",
    "marlin_mini_missing_total",
)
