"""Fixture chaos coverage: names ckpt.write and serve.step (covered) but
never net.flaky (the seeded test-hygiene violation)."""


def test_ckpt_write_survives():
    assert "ckpt.write"


def test_serve_step_survives():
    assert "serve.step"
