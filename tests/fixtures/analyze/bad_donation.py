"""Seeded violation for donation: ``state`` is read after its buffer was
donated to the jitted step."""

import functools

import jax

_step = jax.jit(lambda s, b: s, donate_argnums=(0,))


@functools.partial(jax.jit, donate_argnums=(0,))
def apply_grads(state, grads):
    return state - grads


def train(state, batch):
    out = _step(state, batch)
    return out, state  # read-after-donation: the buffer is deleted


def safe_train(state, grads):
    state = apply_grads(state, grads)  # rebind over the donated ref: safe
    return state
