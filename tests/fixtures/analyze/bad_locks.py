"""Seeded violation for lock-discipline: ``ServeEngine.counter`` is
written from the caller domain (submit) and the worker domain (_run)
without holding the lock either time."""

import threading


class ServeEngine:
    def __init__(self):
        self._lock = threading.Lock()
        self.counter = 0
        self._state = "new"
        self._thread = threading.Thread(target=self._run)

    def submit(self, item):
        self.counter = self.counter + 1  # caller domain, no lock: finding
        return item

    def close(self):
        with self._lock:
            self._state = "closed"  # locked: no finding

    def _run(self):
        while True:
            self.counter = 0  # worker domain, no lock: finding
            with self._lock:
                self._state = "running"  # locked: no finding
