"""Clean fixture: the idiomatic patterns every files-scope check must
accept without a finding. The analyze selftest runs all four files-scope
checks over this file and requires silence."""

import functools
import threading

import jax


@functools.lru_cache(maxsize=8)
def make_program(dim):
    # closure-jit is allowed inside a memoized factory: the cache key IS
    # everything the program closes over
    return jax.jit(lambda x: x * dim)


@functools.partial(jax.jit, donate_argnums=(0,))
def update(state, grads):
    return state - grads


def train(state, grads):
    state = update(state, grads)  # rebinds the donated reference: safe
    return state


def decode_step(state):
    state = update(state, state)
    return state


class ServeEngine:
    """Cross-thread writes, every one under the owning lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0
        self._thread = threading.Thread(target=self._run)

    def submit(self):
        with self._lock:
            self._count += 1

    def _run(self):
        with self._lock:
            self._count = 0
