"""Seeded violations for recompile: a per-call jit creation, a jit built
inside a loop body, and a config knob read at trace time."""

import jax

from marlin_tpu.config import get_config


def make_program(scale):
    # closure-jit with no memoization: a fresh trace+compile per call
    return jax.jit(lambda x: x * scale)


def run_all(xs):
    outs = []
    for x in xs:
        f = jax.jit(lambda v: v + 1)  # jit-in-loop: one compile per item
        outs.append(f(x))
    return outs


@jax.jit
def scaled(x):
    cfg = get_config()  # traced-knob: baked in at trace time
    return x * cfg.matmul_precision
