"""Aux subsystems: failure recovery, tracing/event log, parity fills."""

import numpy as np
import pytest

import jax.numpy as jnp

import marlin_tpu as mt
from marlin_tpu.ops.local import axpy, triu_to_full
from marlin_tpu.utils import EventLog, NonFiniteLossError, ResilientLoop, heartbeat


def test_resilient_loop_recovers_from_exception(tmp_path):
    calls = {"n": 0}

    def step(state, i):
        calls["n"] += 1
        if i == 7 and calls["n"] == 8:  # fail once at step 7
            raise RuntimeError("injected device failure")
        return {"w": state["w"] + 1.0}, float(i)

    loop = ResilientLoop(step, str(tmp_path), checkpoint_every=5, max_retries=2)
    state, metrics = loop.run({"w": jnp.zeros(())}, iterations=12)
    assert float(state["w"]) == 12.0
    assert loop.retries == 1
    # exactly one metric per step — replayed steps must not duplicate
    assert metrics == [float(i) for i in range(12)]


def test_resilient_loop_nonfinite_detection(tmp_path):
    def step(state, i):
        return state, float("nan")

    loop = ResilientLoop(step, str(tmp_path), checkpoint_every=5, max_retries=1)
    with pytest.raises(NonFiniteLossError):
        loop.run({"w": jnp.zeros(())}, iterations=3)


def test_resilient_loop_process_restart(tmp_path):
    def step(state, i):
        return {"w": state["w"] + 1.0}, 0.0

    loop1 = ResilientLoop(step, str(tmp_path), checkpoint_every=5)
    loop1.run({"w": jnp.zeros(())}, iterations=10)
    # a new process resumes from the persisted step-10 checkpoint
    loop2 = ResilientLoop(step, str(tmp_path), checkpoint_every=5)
    state, metrics = loop2.run({"w": jnp.zeros(())}, iterations=15)
    assert float(state["w"]) == 15.0
    assert len(metrics) == 5  # only steps 10..14 ran


def test_heartbeat():
    import jax

    beats = heartbeat()
    assert len(beats) == len(jax.devices())
    assert all(0 <= v < float("inf") for v in beats.values())


def test_heartbeat_reports_all_devices_on_timeout(monkeypatch):
    # a wedged device must not hide the others' status or hang the sweep
    import jax

    import marlin_tpu.utils.failure as failure

    real_block = jax.block_until_ready
    wedged = jax.devices()[1]

    def fake_block(x):
        if x.devices() == {wedged}:
            import time
            time.sleep(60)
        return real_block(x)

    monkeypatch.setattr(failure.jax, "block_until_ready", fake_block)
    with pytest.raises(TimeoutError) as ei:
        heartbeat(timeout_s=3.0)
    res = ei.value.results
    assert res[str(wedged)] == float("inf")
    healthy = [v for k, v in res.items() if k != str(wedged)]
    assert len(healthy) == len(jax.devices()) - 1
    assert all(v < float("inf") for v in healthy)
    # non-raising form returns the same map
    monkeypatch.setattr(failure.jax, "block_until_ready", real_block)
    ok = heartbeat(timeout_s=30.0, raise_on_failure=False)
    assert all(v < float("inf") for v in ok.values())


def test_heartbeat_records_device_errors(monkeypatch):
    # a dead device typically ERRORS immediately; the exception must surface,
    # not be mislabeled as a 30s timeout
    import jax

    import marlin_tpu.utils.failure as failure

    real_put = jax.device_put
    dead = jax.devices()[2]

    def fake_put(x, d=None):
        if d == dead:
            raise RuntimeError("chip lost")
        return real_put(x, d)

    monkeypatch.setattr(failure.jax, "device_put", fake_put)
    res = heartbeat(timeout_s=10.0, raise_on_failure=False)
    assert res[str(dead)] == float("inf")
    assert "chip lost" in str(res.errors[str(dead)])
    with pytest.raises(TimeoutError, match="chip lost"):
        heartbeat(timeout_s=10.0)


def test_event_log(tmp_path):
    log = EventLog(str(tmp_path / "ev.jsonl"))
    log.event("step", step=1, loss=0.5)
    with log.timed("matmul", n=64):
        pass
    log.close()
    events = log.read()
    assert events[0]["kind"] == "step" and events[0]["loss"] == 0.5
    assert events[1]["kind"] == "matmul" and events[1]["seconds"] >= 0
    assert events[1]["ok"] is True


def test_event_log_timed_records_on_raise(tmp_path):
    """timed() must land its record even when the body raises — a crash is
    exactly when the post-mortem needs the timing — tagged ok=False."""
    log = EventLog(str(tmp_path / "ev.jsonl"))
    with pytest.raises(RuntimeError, match="boom"):
        with log.timed("step", step=3):
            raise RuntimeError("boom")
    log.close()
    (rec,) = log.read()
    assert rec["kind"] == "step" and rec["step"] == 3
    assert rec["ok"] is False and rec["seconds"] >= 0


def test_event_log_concurrent_writers(tmp_path):
    """event() is called from serving/prefetch worker threads concurrently;
    every line of the shared-handle JSONL stream must stay parseable and no
    record may be lost (the write+flush lock)."""
    import threading

    log = EventLog(str(tmp_path / "ev.jsonl"))
    n_threads, per_thread = 8, 200

    def writer(tid):
        for i in range(per_thread):
            log.event("w", tid=tid, i=i, pad="x" * 64)

    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    log.close()
    log.close()  # idempotent — teardown paths may race a second close
    events = log.read()  # json.loads on every line — interleaving would blow
    assert len(events) == n_threads * per_thread
    seen = {(e["tid"], e["i"]) for e in events}
    assert len(seen) == n_threads * per_thread


def test_axpy_and_triu_to_full():
    x = jnp.arange(4.0)
    y = jnp.ones(4)
    np.testing.assert_allclose(axpy(2.0, x, y), np.arange(4) * 2 + 1)
    u = jnp.triu(jnp.arange(9.0).reshape(3, 3))
    full = triu_to_full(u)
    np.testing.assert_allclose(full, np.asarray(full).T)
    np.testing.assert_allclose(np.triu(np.asarray(full)), np.asarray(u))


def test_coo_to_block_matrix(mesh):
    coo = mt.CoordinateMatrix.from_entries([(0, 1, 2.0), (2, 0, 3.0)], mesh=mesh)
    bm = coo.to_block_matrix()
    assert isinstance(bm, mt.BlockMatrix)
    expected = np.zeros((3, 2), np.float32)
    expected[0, 1], expected[2, 0] = 2.0, 3.0
    np.testing.assert_allclose(bm.to_numpy(), expected)


def test_multiply_gramian_by(mesh):
    rng = np.random.default_rng(0)
    a = rng.standard_normal((20, 6)).astype(np.float32)
    m = mt.DenseVecMatrix.from_array(a, mesh)
    v = rng.standard_normal(6).astype(np.float32)
    out = m.multiply_gramian_by(v)
    np.testing.assert_allclose(out.to_numpy(), a.T @ (a @ v), rtol=1e-4, atol=1e-4)


def test_row_exchange(mesh):
    a = np.arange(12.0).reshape(4, 3).astype(np.float32)
    m = mt.DenseVecMatrix.from_array(a, mesh)
    perm = [2, 0, 3, 1]
    np.testing.assert_allclose(m.row_exchange(perm).to_numpy(), a[perm])
    with pytest.raises(ValueError):
        m.row_exchange([0, 1])


def test_to_dense_blocks_identity(mesh):
    bm = mt.BlockMatrix.ones(4, 4, mesh=mesh)
    assert bm.to_dense_blocks() is bm


def test_checkpoint_shape_mismatch_raises(tmp_path):
    import jax.numpy as jnp

    from marlin_tpu.io.checkpoint import load_checkpoint, save_checkpoint

    save_checkpoint({"w": jnp.zeros((4, 4))}, str(tmp_path), step=1)
    with pytest.raises(ValueError):
        load_checkpoint({"w": jnp.zeros((8, 8))}, str(tmp_path))


def test_checkpoint_restores_sharding(tmp_path, mesh):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from marlin_tpu.io.checkpoint import load_checkpoint, save_checkpoint

    sh = NamedSharding(mesh, P("rows", None))
    w = jax.device_put(jnp.arange(16.0).reshape(8, 2), sh)
    save_checkpoint({"w": w}, str(tmp_path), step=3)
    restored, step = load_checkpoint({"w": w}, str(tmp_path))
    assert step == 3
    assert restored["w"].sharding == sh
    np.testing.assert_array_equal(np.asarray(restored["w"]), np.asarray(w))
