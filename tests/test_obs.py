"""Observability-layer suite: registry/exposition, trace propagation,
EventLog torn-line tolerance + rotation, reservoir sampling, the compile
bridge, the live-serve scrape, and the analyzer golden.

The cross-thread propagation tests are the load-bearing ones: the span
context must survive the hop onto the serving worker thread (every record
one request produces joins one trace) and onto prefetch producer threads —
contextvars do NOT cross ``threading.Thread`` by default, so these assert
the explicit capture/use handoff actually happens everywhere it must.
"""

import json
import os
import random
import threading
import urllib.request

import numpy as np
import pytest

from marlin_tpu import obs
from marlin_tpu.obs import collectors, trace
from marlin_tpu.obs.metrics import MetricsRegistry, percentile
from marlin_tpu.obs.report import analyze, load_events
from marlin_tpu.serving.metrics import Reservoir, ServeMetrics
from marlin_tpu.utils.tracing import EventLog, set_default_event_log

FIXTURE = os.path.join(os.path.dirname(__file__), "..", "tools", "fixtures",
                       "obs_events.jsonl")
GOLDEN = os.path.join(os.path.dirname(__file__), "..", "tools", "fixtures",
                      "obs_report_golden.txt")


@pytest.fixture()
def default_log(tmp_path):
    """A fresh default EventLog, restored afterwards."""
    log = EventLog(str(tmp_path / "events.jsonl"))
    prev = set_default_event_log(log)
    yield log
    set_default_event_log(prev)
    log.close()


# ------------------------------------------------------------------ registry


def test_registry_counter_gauge_labels():
    reg = MetricsRegistry()
    c = reg.counter("c_total", "help", labelnames=("status",))
    c.labels(status="ok").inc()
    c.labels(status="ok").inc(2)
    c.labels("err").inc()  # positional addressing, same family
    assert c.labels(status="ok").value == 3
    assert c.labels(status="err").value == 1
    g = reg.gauge("g")
    g.set(5)
    g.dec(2)
    assert g.value == 3
    with pytest.raises(ValueError, match="counter increment"):
        c.labels(status="ok").inc(-1)
    with pytest.raises(ValueError, match="label"):
        c.labels(wrong="x")
    with pytest.raises(ValueError, match="has labels"):
        c.inc()  # labeled family needs .labels()


def test_registry_idempotent_and_conflict():
    reg = MetricsRegistry()
    a = reg.counter("x_total", "h", labelnames=("k",))
    b = reg.counter("x_total", "h", labelnames=("k",))
    assert a is b  # subsystems re-register freely (one family per name)
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("x_total")
    with pytest.raises(ValueError, match="already registered"):
        reg.counter("x_total", labelnames=("other",))


def test_histogram_buckets_cumulative():
    reg = MetricsRegistry()
    h = reg.histogram("h_seconds", "h", buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.5, 5.0):
        h.observe(v)
    text = reg.render()
    assert 'h_seconds_bucket{le="0.01"} 1' in text
    assert 'h_seconds_bucket{le="0.1"} 2' in text
    assert 'h_seconds_bucket{le="1"} 3' in text
    assert 'h_seconds_bucket{le="+Inf"} 4' in text
    assert "h_seconds_count 4" in text
    assert "h_seconds_sum 5.555" in text


def test_render_format_and_escaping():
    reg = MetricsRegistry()
    c = reg.counter("esc_total", 'says "hi"', labelnames=("path",))
    c.labels(path='a"b\\c\nd').inc()
    text = reg.render()
    assert "# TYPE esc_total counter" in text
    assert '# HELP esc_total says "hi"' in text
    assert r'esc_total{path="a\"b\\c\nd"} 1' in text


def test_registry_collector_runs_at_render_and_may_fail():
    reg = MetricsRegistry()
    g = reg.gauge("live")
    reg.add_collector(lambda: g.set(42))

    def broken():
        raise RuntimeError("probe died")

    reg.add_collector(broken)  # must not fail the scrape
    assert "live 42" in reg.render()
    reg.remove_collector(broken)


# ------------------------------------------------------------------- tracing


def test_span_nesting_and_context_fields():
    assert trace.current() is None
    assert trace.context_fields() == {}
    with trace.span("outer") as outer:
        assert trace.current() is outer
        assert outer.trace_id == outer.span_id  # root is recognizable
        f = trace.context_fields()
        assert f == {"trace_id": outer.trace_id, "span_id": outer.span_id}
        with trace.span("inner") as inner:
            assert inner.trace_id == outer.trace_id
            assert inner.parent_id == outer.span_id
            assert inner.span_id != outer.span_id
            assert trace.context_fields()["parent_id"] == outer.span_id
        assert trace.current() is outer
    assert trace.current() is None


def test_eventlog_records_carry_span_context(tmp_path):
    log = EventLog(str(tmp_path / "ev.jsonl"))
    log.event("bare")
    with trace.span("work") as ctx:
        log.event("traced", x=1)
    log.close()
    bare, traced = log.read()
    assert "trace_id" not in bare
    assert traced["trace_id"] == ctx.trace_id
    assert traced["span_id"] == ctx.span_id


def test_span_survives_explicit_thread_handoff(tmp_path):
    log = EventLog(str(tmp_path / "ev.jsonl"))
    with trace.span("parent") as ctx:
        captured = trace.capture()

    def worker():
        with trace.use(captured):
            log.event("from_thread")

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    log.close()
    (rec,) = log.read()
    assert rec["trace_id"] == ctx.trace_id


# ------------------------------------------------- EventLog torn line + rotation


def test_eventlog_read_skips_torn_final_line(tmp_path):
    path = str(tmp_path / "ev.jsonl")
    log = EventLog(path)
    log.event("a", i=1)
    log.event("b", i=2)
    log.close()
    with open(path, "a") as f:  # a crash mid-write: partial JSON, no newline
        f.write('{"t": 1.0, "kind": "tor')
    with pytest.warns(RuntimeWarning, match="torn/partial"):
        recs = log.read()
    assert [r["kind"] for r in recs] == ["a", "b"]
    assert log.last_read_skipped == 1


def test_eventlog_rotation(tmp_path):
    path = str(tmp_path / "rot.jsonl")
    log = EventLog(path, max_bytes=300)
    for i in range(40):
        log.event("tick", i=i, pad="x" * 20)
    log.close()
    assert os.path.exists(path + ".1")
    assert os.path.exists(path + ".2")
    assert not os.path.exists(path + ".3")  # two backups, oldest dropped
    assert os.path.getsize(path) <= 300
    recs = log.read(include_rotated=True)
    assert log.last_read_skipped == 0
    idx = [r["i"] for r in recs]
    assert idx == sorted(idx)  # rotated stream reads oldest-first, in order
    assert idx[-1] == 39  # newest record is in the live file
    assert len(recs) > len(log.read())  # backups really contribute


def test_eventlog_rotation_follows_config(tmp_path):
    from marlin_tpu.config import config_context

    path = str(tmp_path / "cfg.jsonl")
    log = EventLog(path)  # max_bytes=None -> config at write time
    with config_context(obs_log_max_bytes=200):
        for i in range(20):
            log.event("tick", i=i, pad="y" * 20)
    log.close()
    assert os.path.exists(path + ".1")


def test_eventlog_concurrent_writers_no_torn_lines(tmp_path):
    """8 threads x 200 events through one log (rotation on, tiny bound):
    every line parses and every event lands exactly once — the lock really
    covers write+rotate."""
    path = str(tmp_path / "stress.jsonl")
    # bound sized so the stream rotates (~90 KB of records vs 64 KB) but
    # main + two backups retain everything — retention is assertable
    log = EventLog(path, max_bytes=64_000)
    n_threads, n_events = 8, 200

    def writer(tid):
        for i in range(n_events):
            log.event("w", tid=tid, i=i)

    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    log.close()
    assert os.path.exists(path + ".1")  # the stream really rotated
    recs = log.read(include_rotated=True)
    assert log.last_read_skipped == 0
    seen = {(r["tid"], r["i"]) for r in recs}
    assert len(seen) == len(recs), "duplicated records"
    assert len(seen) == n_threads * n_events, "records lost or torn"


# ------------------------------------------------------------- reservoirs


def test_reservoir_uniform_not_first_n_biased():
    r = Reservoir(100, random.Random(7))
    for v in range(10_000):
        r.add(float(v))
    assert r.n == 10_000
    assert len(r.items) == 100
    # a first-N-then-drop reservoir would hold only 0..99; uniform sampling
    # must keep late values and an unbiased mean
    assert max(r.items) > 9_000
    mean = sum(r.items) / len(r.items)
    assert abs(mean - 4999.5) < 1_000


def test_serve_metrics_percentiles_cover_whole_run():
    """The regression the reservoir swap fixes: latencies that degrade over
    the run must show up in p50 even past keep_latencies samples."""
    m = ServeMetrics(keep_latencies=64, rng=random.Random(3))
    for i in range(4096):
        m.record_result(rid=i, status="ok", total_s=float(i))
    snap = m.snapshot()
    # first-64-then-drop would report p50 ~= 32; uniform sampling tracks
    # the full stream (true p50 ~= 2048)
    assert snap["p50_total_s"] > 1_000
    assert snap["completed"] == 4096


# ------------------------------------------------------- timer / StageTimes


def test_timer_routes_through_default_log(default_log, capsys):
    from marlin_tpu.utils.profiling import timer

    with trace.span("bench") as ctx:
        with timer("unit-test", quiet=True):
            pass
    recs = [r for r in default_log.read() if r["kind"] == "timer"]
    assert len(recs) == 1
    assert recs[0]["label"] == "unit-test"
    assert recs[0]["seconds"] >= 0
    assert recs[0]["trace_id"] == ctx.trace_id
    assert capsys.readouterr().out == ""  # quiet still prints nothing


def test_stage_times_feed_registry():
    from marlin_tpu.obs.metrics import get_registry
    from marlin_tpu.utils.profiling import StageTimes

    fam = get_registry().counter("marlin_stage_seconds_total",
                                 labelnames=("stage",))
    before = fam.labels(stage="obs_test_stage").value
    st = StageTimes()
    st.add("obs_test_stage", 0.25)
    st.add("obs_test_stage", 0.25)
    assert fam.labels(stage="obs_test_stage").value == pytest.approx(
        before + 0.5)


# ------------------------------------------------------------ compile bridge


def test_compile_bridge_counts_and_logs(default_log):
    import jax

    collectors.install_compile_metrics()
    fam = obs.get_registry().counter("marlin_compile_total")
    before_metric = fam.value
    before_count = collectors.compile_count()

    @jax.jit
    def f(x):
        return x * 1.00042 + 17.0

    f(np.float32(2.0))
    assert collectors.compile_count() - before_count >= 1
    assert fam.value - before_metric >= 1
    compiles = [r for r in default_log.read() if r["kind"] == "compile"]
    assert compiles and all(r["seconds"] > 0 for r in compiles)


# --------------------------------------------------------------- exposition


def test_metrics_server_scrape_healthz_404():
    with obs.MetricsServer(port=0) as srv:
        base = f"http://127.0.0.1:{srv.port}"
        text = urllib.request.urlopen(base + "/metrics",
                                      timeout=10).read().decode()
        assert "# TYPE marlin_compile_total counter" in text
        assert "# TYPE marlin_prefetch_chunks_total counter" in text
        ok = urllib.request.urlopen(base + "/healthz",
                                    timeout=10).read().decode()
        assert ok == "ok\n"
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(base + "/nope", timeout=10)


def test_start_from_config(tmp_path):
    from marlin_tpu.config import config_context

    assert obs.start_from_config() is None  # default: disabled
    with config_context(obs_http_port=0):
        srv = obs.start_from_config()
    try:
        assert srv is not None and srv.port > 0
        text = urllib.request.urlopen(srv.url, timeout=10).read().decode()
        assert "marlin_compile_total" in text
    finally:
        srv.close()


# -------------------------------------------- cross-thread: serving + prefetch


HEADS = 2


@pytest.fixture(scope="module")
def lm_params():
    from marlin_tpu.models import TransformerLM

    return TransformerLM(vocab=32, d_model=16, heads=HEADS, layers=2,
                         seed=9).init_params()


def test_serving_request_records_join_one_trace(lm_params, default_log):
    from marlin_tpu.serving import Request, ServeEngine

    with ServeEngine(lm_params, HEADS, buckets=((8, 4),), max_batch=4,
                     max_wait_ms=0.0, queue_depth=16) as eng:
        handles = [eng.submit(Request(prompt=[1 + i, 2, 3], steps=3))
                   for i in range(3)]
        eng.drain()
    results = [h.result(timeout=5) for h in handles]
    assert all(r.ok for r in results)
    serve = [r for r in default_log.read() if r["kind"] == "serve"]
    by_rid = {}
    for rec in serve:
        if "rid" in rec:
            by_rid.setdefault(rec["rid"], []).append(rec)
    assert len(by_rid) == 3
    tids = set()
    for rid, recs in by_rid.items():
        evs = {r["ev"] for r in recs}
        assert {"enqueue", "result"} <= evs
        assert "prefill" in evs  # row-level default: prefill carries rid
        rid_tids = {r.get("trace_id") for r in recs}
        assert len(rid_tids) == 1 and None not in rid_tids, (
            f"rid {rid} records span traces {rid_tids}")
        tids.add(rid_tids.pop())
    # submitted outside any span: each request is its own root trace
    assert len(tids) == 3


def test_serving_trace_joins_caller_span(lm_params, default_log):
    from marlin_tpu.serving import Request, ServeEngine

    with trace.span("client") as client:
        with ServeEngine(lm_params, HEADS, buckets=((8, 4),), max_batch=4,
                         max_wait_ms=0.0, queue_depth=16) as eng:
            h = eng.submit(Request(prompt=[1, 2, 3], steps=2))
            eng.drain()
    assert h.result(timeout=5).ok
    serve = [r for r in default_log.read()
             if r["kind"] == "serve" and "rid" in r]
    assert serve
    # with a caller span active, the request joins the CALLER's trace
    assert {r["trace_id"] for r in serve} == {client.trace_id}
    enq = next(r for r in serve if r["ev"] == "enqueue")
    assert enq["parent_id"] == client.span_id


def test_prefetch_producer_threads_inherit_span(default_log):
    from marlin_tpu.parallel.prefetch import ChunkPrefetcher

    probe_ctx = []

    def transform(c):
        # runs on a marlin-prefetch-* worker thread
        probe_ctx.append(trace.current())
        default_log.event("probe", n=int(c.sum()))
        return c

    chunks = [np.ones((4, 4), np.float32) * i for i in range(5)]
    with trace.span("stream") as ctx:
        out = list(ChunkPrefetcher(chunks, transform, device_put=False))
    assert len(out) == 5
    assert all(c is not None and c.trace_id == ctx.trace_id
               for c in probe_ctx)
    recs = default_log.read()
    probes = [r for r in recs if r["kind"] == "probe"]
    assert len(probes) == 5
    assert {r["trace_id"] for r in probes} == {ctx.trace_id}
    summary = next(r for r in recs if r["kind"] == "prefetch")
    assert summary["trace_id"] == ctx.trace_id


def test_checkpoint_save_load_traced(tmp_path, default_log):
    import jax.numpy as jnp

    from marlin_tpu.io.checkpoint import load_checkpoint, save_checkpoint

    state = {"w": jnp.arange(8.0), "step_scale": jnp.float32(2.0)}
    save_checkpoint(state, str(tmp_path / "ck"), step=3)
    restored, step = load_checkpoint(state, str(tmp_path / "ck"))
    assert step == 3
    np.testing.assert_array_equal(np.asarray(restored["w"]),
                                  np.arange(8.0))
    ckpts = [r for r in default_log.read() if r["kind"] == "ckpt"]
    assert [r["ev"] for r in ckpts] == ["save", "load"]
    assert all(r["ok"] and r["seconds"] >= 0 and r["trace_id"]
               for r in ckpts)
    # save and load were separate operations: distinct traces
    assert ckpts[0]["trace_id"] != ckpts[1]["trace_id"]


# ----------------------------------------------------- live-serve scrape e2e


def test_scrape_during_live_serve_returns_live_series(lm_params,
                                                      default_log):
    """The acceptance path: while an engine is serving (not yet drained) a
    /metrics scrape must carry nonzero serving + prefetch + compile series
    — the three blind spots the obs layer closes."""
    from marlin_tpu.parallel.streaming import streamed_gramian
    from marlin_tpu.serving import Request, ServeEngine

    collectors.install_compile_metrics()
    # tick the prefetch series (any streamed op runs the pipeline)
    streamed_gramian(iter([np.ones((8, 4), np.float32)] * 3), prefetch=True)
    with obs.MetricsServer(port=0) as srv:
        with ServeEngine(lm_params, HEADS, buckets=((8, 4),), max_batch=4,
                         max_wait_ms=0.0, queue_depth=32) as eng:
            handles = [eng.submit(Request(prompt=[1, 2, i % 7 + 1], steps=4))
                       for i in range(8)]
            text = urllib.request.urlopen(srv.url, timeout=10).read().decode()
            eng.drain()
        assert all(h.result(timeout=5).ok for h in handles)

    def value(name):
        for line in text.splitlines():
            if line.startswith(name + " ") or line.startswith(name + "{"):
                return float(line.rsplit(" ", 1)[1])
        return None

    assert value("marlin_serve_submitted_total") >= 8
    assert value("marlin_prefetch_chunks_total") >= 3
    assert value("marlin_compile_total") >= 1
    # gauges exist (live queue state; may be any current value)
    for g in ("marlin_serve_queue_depth", "marlin_serve_kv_inflight_bytes",
              "marlin_serve_slot_occupancy"):
        assert f"# TYPE {g} gauge" in text
    # the planner-budget gauge the KV admission gate reasons against
    assert value("marlin_hbm_planner_budget_bytes") > 0


# ------------------------------------------------------------------ analyzer


def test_report_golden_on_fixture():
    events, skipped = load_events(FIXTURE)
    assert skipped == 1  # the fixture ends in a torn line, by construction
    got = analyze(events, skipped)
    with open(GOLDEN) as f:
        assert got == f.read()


def test_report_main_cli(tmp_path, capsys):
    from marlin_tpu.obs.report import main

    assert main([FIXTURE]) == 0
    out = capsys.readouterr().out
    assert "trace join: 6/6 requests" in out
    assert main([]) == 2
    assert main([str(tmp_path / "missing.jsonl")]) == 1


def test_report_empty_stream():
    assert analyze([]) == "== marlin_tpu.obs.report ==\nevents: 0\n"


# --------------------------------------------------- analysis time windows


def test_parse_when_forms():
    from marlin_tpu.obs.report import parse_when

    assert parse_when("1234.5") == 1234.5
    assert parse_when("5m ago", now=1000.0) == 700.0
    assert parse_when("2h ago", now=10000.0) == 10000.0 - 7200.0
    assert parse_when("30s ago", now=100.0) == 70.0
    assert parse_when("1d ago", now=90000.0) == 90000.0 - 86400.0
    # ISO-8601; a naive stamp is taken as UTC (EventLog stamps time.time())
    assert parse_when("1970-01-01T00:10:00+00:00") == 600.0
    assert parse_when("1970-01-01T00:10:00") == 600.0
    with pytest.raises(ValueError, match="cannot parse time"):
        parse_when("next tuesday")


def test_load_events_window():
    all_events, _ = load_events(FIXTURE)
    windowed, skipped = load_events(FIXTURE, since=1004.0, until=1009.0)
    assert skipped == 1
    assert 0 < len(windowed) < len(all_events)
    assert all(1004.0 <= r["t"] <= 1009.0 for r in windowed)
    # a record with no numeric t is kept, not silently dropped
    import tempfile

    with tempfile.NamedTemporaryFile("w", suffix=".jsonl",
                                     delete=False) as f:
        f.write(json.dumps({"kind": "x", "t": 5.0}) + "\n")
        f.write(json.dumps({"kind": "y"}) + "\n")
        path = f.name
    try:
        recs, _ = load_events(path, since=100.0)
        assert [r["kind"] for r in recs] == ["y"]
    finally:
        os.unlink(path)


WINDOW_GOLDEN = os.path.join(os.path.dirname(__file__), "..", "tools",
                             "fixtures", "obs_report_window_golden.txt")


def test_report_cli_since_until_golden(capsys):
    from marlin_tpu.obs.report import main

    assert main(["--since", "1004", "--until", "1009", FIXTURE]) == 0
    out = capsys.readouterr().out
    with open(WINDOW_GOLDEN) as f:
        assert out == f.read()
    # flag error paths fail loudly with usage, not a traceback
    assert main(["--since"]) == 2
    assert main(["--since", "next tuesday", FIXTURE]) == 2
    assert main(["--since", "1004", FIXTURE, "extra.jsonl"]) == 2
    capsys.readouterr()


# ----------------------------------------------- concurrent scrape stress


def test_concurrent_scrape_no_torn_exposition(lm_params, default_log):
    """8 client threads hammering /metrics, /healthz, /debug/slo and
    /debug/kvpool during a live serve: every response is well-formed (no
    500s, no torn exposition) and the serve is undisturbed."""
    import marlin_tpu as mt
    from marlin_tpu.serving import Request, ServeEngine

    slo = ({"name": "ttft", "metric": "p95:marlin_serve_ttft_seconds",
            "target": 30.0, "window_s": 60.0},)
    with mt.config_context(serve_slo=slo, serve_slo_eval_interval_s=0.05,
                           serve_ts_bucket_s=0.5):
        eng = ServeEngine(lm_params, HEADS, buckets=((8, 4),), max_batch=4,
                          max_wait_ms=0.0, queue_depth=64)
    failures: list[str] = []

    def hammer(base, n=6):
        paths = ("/metrics", "/healthz", "/debug/slo", "/debug/kvpool")
        for i in range(n):
            for p in paths:
                try:
                    with urllib.request.urlopen(base + p, timeout=10) as r:
                        body = r.read().decode()
                        code = r.status
                except urllib.error.HTTPError as e:
                    body, code = e.read().decode(), e.code
                except Exception as e:  # connection-level failure
                    failures.append(f"{p}: {type(e).__name__}: {e}")
                    continue
                if code >= 500:
                    failures.append(f"{p}: HTTP {code}")
                elif p == "/metrics":
                    if (not body.endswith("\n")
                            or "# TYPE marlin_serve_submitted_total"
                            not in body):
                        failures.append(f"{p}: torn exposition")
                else:
                    try:
                        json.loads(body)
                    except ValueError:
                        failures.append(f"{p}: torn JSON body")

    try:
        with obs.MetricsServer(port=0) as srv:
            base = srv.url.rsplit("/metrics", 1)[0]
            handles = [eng.submit(Request(prompt=[1, 2, i % 7 + 1],
                                          steps=3))
                       for i in range(16)]
            threads = [threading.Thread(target=hammer, args=(base,))
                       for _ in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert not any(t.is_alive() for t in threads)
            results = [h.result(timeout=60) for h in handles]
    finally:
        eng.close()
    assert not failures, failures[:10]
    assert all(r.ok for r in results)
