"""Text-format IO roundtrips (MTUtils loaders / save formats) and sharded
checkpointing. The reference never tests its loaders (SURVEY.md §4)."""

import os

import jax.numpy as jnp
import numpy as np
import pytest

import marlin_tpu as mt
from marlin_tpu.io import (
    load_checkpoint,
    load_sharded,
    save_checkpoint,
    save_sharded,
)


def test_text_roundtrip(tmp_path, mesh, a4):
    m = mt.DenseVecMatrix.from_array(a4, mesh)
    p = str(tmp_path / "mat.txt")
    m.save_to_file_system(p)
    loaded = mt.load_matrix_file(p, mesh)
    np.testing.assert_allclose(loaded.to_numpy(), a4)


def test_text_format_exact(tmp_path, mesh):
    # the exact reference line format: rowIdx:v,v,...
    a = np.array([[1.5, 2.0], [3.0, 4.25]])
    m = mt.DenseVecMatrix.from_array(a, mesh)
    p = str(tmp_path / "m.txt")
    m.save_to_file_system(p)
    lines = open(p).read().strip().split("\n")
    assert lines[0].startswith("0:") and "," in lines[0]
    # reference parser tolerance: spaces or commas
    with open(p, "w") as f:
        f.write("0:1.5 2.0\n1:3.0, 4.25\n")
    np.testing.assert_allclose(mt.load_matrix_file(p, mesh).to_numpy(), a)


def test_block_roundtrip(tmp_path, mesh, a4):
    m = mt.BlockMatrix.from_array(a4, mesh)
    p = str(tmp_path / "blk.txt")
    m.save_to_file_system(p, fmt="block")
    loaded = mt.load_block_matrix_file(p, mesh)
    np.testing.assert_allclose(loaded.to_numpy(), a4)


def test_coordinate_loader(tmp_path, mesh):
    p = str(tmp_path / "coo.txt")
    with open(p, "w") as f:
        f.write("0,0,1.5\n1 2 2.0\n2,1,3.0,999999\n")  # movielens-style timestamp
    coo = mt.load_coordinate_matrix(p, mesh=mesh)
    expected = np.zeros((3, 3), np.float32)
    expected[0, 0], expected[1, 2], expected[2, 1] = 1.5, 2.0, 3.0
    np.testing.assert_allclose(coo.to_numpy(), expected)


def test_svm_loader(tmp_path, mesh):
    p = str(tmp_path / "svm.txt")
    with open(p, "w") as f:
        f.write("0 1:0.5 3:2.0\n1 2:1.0\n")
    m = mt.load_svm_den_vec_matrix(p, vector_len=3, mesh=mesh)
    expected = np.array([[0.5, 0.0, 2.0], [0.0, 1.0, 0.0]])
    np.testing.assert_allclose(m.to_numpy(), expected)


def test_directory_loader(tmp_path, mesh):
    d = tmp_path / "parts"
    d.mkdir()
    (d / "part0").write_text("0:1.0,2.0\n")
    (d / "part1").write_text("1:3.0,4.0\n")
    m = mt.load_matrix_file(str(d), mesh)
    np.testing.assert_allclose(m.to_numpy(), [[1.0, 2.0], [3.0, 4.0]])


def test_description_sidecar(tmp_path, mesh, a4):
    m = mt.DenseVecMatrix.from_array(a4, mesh)
    p = str(tmp_path / "out" / "mat.txt")
    os.makedirs(os.path.dirname(p), exist_ok=True)
    m.save_with_description(p)
    desc = open(os.path.join(os.path.dirname(p), "_description")).read()
    assert "rows: 4" in desc and "cols: 4" in desc


def test_sharded_checkpoint(tmp_path, mesh):
    m = mt.BlockMatrix.random(0, 10, 12, mesh=mesh)
    path = str(tmp_path / "ckpt")
    save_sharded(m.data, path)
    loaded = load_sharded(path, m.data.sharding)
    np.testing.assert_array_equal(np.asarray(loaded), np.asarray(m.data))


def test_training_checkpoint(tmp_path):
    import jax.numpy as jnp

    state = {"w": jnp.arange(6.0).reshape(2, 3), "step_scale": jnp.float32(0.5)}
    save_checkpoint(state, str(tmp_path / "t"), step=7)
    restored, step = load_checkpoint(state, str(tmp_path / "t"))
    assert step == 7
    np.testing.assert_array_equal(np.asarray(restored["w"]), np.asarray(state["w"]))


def test_coordinate_save_roundtrip(tmp_path, mesh):
    coo = mt.CoordinateMatrix.from_entries(
        [(0, 1, 1.5), (2, 0, -2.25), (3, 3, 0.125)], mesh=mesh)
    p = str(tmp_path / "coo_out.txt")
    coo.save_to_file_system(p)
    back = mt.load_coordinate_matrix(p, shape=coo.shape, mesh=mesh)
    np.testing.assert_allclose(back.to_numpy(), coo.to_numpy())


def test_block_text_missing_first_blocks(tmp_path, mesh):
    # extents must come from ANY present block per grid row/column — a writer
    # omitting all-zero blocks (here: the whole first block row/col absent from
    # position (0,0)) must still load, and a fully-absent grid row must raise
    # a format error, not a KeyError
    from marlin_tpu.io.text import _blocks_from_lines

    # grid 2x2, block (0,0) omitted; row 0 extent comes from (0,1), col 0 from (1,0)
    lines = [
        "0-1-2-2:5 6 7 8",   # (0,1): column-major 2x2 -> [[5,7],[6,8]]
        "1-0-2-2:1 2 3 4",   # (1,0)
        "1-1-2-2:9 10 11 12",
    ]
    out = _blocks_from_lines(lines)
    expect = np.zeros((4, 4))
    expect[0:2, 2:4] = np.array([[5, 7], [6, 8]])
    expect[2:4, 0:2] = np.array([[1, 3], [2, 4]])
    expect[2:4, 2:4] = np.array([[9, 11], [10, 12]])
    np.testing.assert_allclose(out, expect)

    with pytest.raises(ValueError, match="no blocks at all"):
        _blocks_from_lines(["1-0-2-2:1 2 3 4"])  # grid row 0 entirely absent


def test_checkpoint_leaf_count_validated(tmp_path):
    import jax.numpy as jnp

    state = {"w": jnp.arange(6.0).reshape(2, 3), "b": jnp.zeros(3)}
    save_checkpoint(state, str(tmp_path / "t"), step=1)
    smaller = {"w": jnp.zeros((2, 3))}
    with pytest.raises(ValueError, match="leaves"):
        load_checkpoint(smaller, str(tmp_path / "t"))


def test_remote_fs_roundtrip(mesh, a4):
    """URL-scheme paths route through the fsspec hook (reference parity:
    HDFS/Tachyon URIs, MTUtils.scala:350-392) — exercised with the in-memory
    filesystem, no network."""
    import fsspec

    from marlin_tpu.io.text import load_matrix_file, save_matrix

    a = mt.DenseVecMatrix.from_array(a4, mesh)
    save_matrix(a, "memory://marlin/remote/a.txt", description=True)
    back = load_matrix_file("memory://marlin/remote/a.txt", mesh)
    np.testing.assert_allclose(back.to_numpy(), a4)
    memfs = fsspec.filesystem("memory")
    assert memfs.isfile("/marlin/remote/_description")


def test_remote_fs_directory_loader(mesh):
    """Directory concatenation (wholeTextFiles) over a remote scheme."""
    import fsspec

    from marlin_tpu.io.text import load_matrix_file

    memfs = fsspec.filesystem("memory")
    memfs.makedirs("/marlin/dir", exist_ok=True)
    with memfs.open("memory://marlin/dir/part0", "w") as f:
        f.write("0:1.0,2.0\n")
    with memfs.open("memory://marlin/dir/part1", "w") as f:
        f.write("1:3.0,4.0\n")
    with memfs.open("memory://marlin/dir/_meta", "w") as f:
        f.write("ignored\n")
    back = load_matrix_file("memory://marlin/dir", mesh)
    np.testing.assert_allclose(back.to_numpy(), [[1.0, 2.0], [3.0, 4.0]])


def test_register_filesystem_override(mesh, a4, tmp_path):
    """A user-registered filesystem wins over fsspec for its scheme."""
    import fsspec

    from marlin_tpu.io import register_filesystem
    from marlin_tpu.io.text import load_matrix_file, save_matrix

    class Local(fsspec.AbstractFileSystem):
        """Trivial 'remote' FS backed by tmp_path."""

        def _real(self, p):
            return str(tmp_path / p.split("://", 1)[-1].lstrip("/"))

        def open(self, p, mode="r", **kw):
            return open(self._real(p), mode)

        def isdir(self, p):
            import os
            return os.path.isdir(self._real(p))

        def isfile(self, p):
            import os
            return os.path.isfile(self._real(p))

        def ls(self, p, **kw):
            import os
            return [p.rstrip("/") + "/" + n for n in os.listdir(self._real(p))]

        def makedirs(self, p, exist_ok=False):
            import os
            os.makedirs(self._real(p), exist_ok=exist_ok)

    register_filesystem("myfs", Local())
    try:
        a = mt.DenseVecMatrix.from_array(a4, mesh)
        save_matrix(a, "myfs://box/a.txt")
        back = load_matrix_file("myfs://box/a.txt", mesh)
        np.testing.assert_allclose(back.to_numpy(), a4)
        assert (tmp_path / "box" / "a.txt").exists()
    finally:
        register_filesystem("myfs", None)


def test_remote_sharded_checkpoint_roundtrip(mesh):
    """save_sharded/load_sharded over a URL scheme (the checkpoint analog of
    the reference's save-to-HDFS), including an elastic restore onto a
    smaller mesh straight from the remote store."""
    import fsspec
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from marlin_tpu.io.checkpoint import load_sharded, save_sharded

    a = mt.BlockMatrix.random(3, 33, 17, mesh=mesh)
    save_sharded(a.data, "memory://marlin/ckpt/arr")
    memfs = fsspec.filesystem("memory")
    assert any("manifest_" in str(f)
               for f in memfs.ls("/marlin/ckpt/arr", detail=False))

    back = load_sharded("memory://marlin/ckpt/arr", sharding=a.data.sharding)
    np.testing.assert_array_equal(np.asarray(back), np.asarray(a.data))

    small = mt.create_mesh((2, 2), devices=jax.devices()[:4])
    elastic = load_sharded("memory://marlin/ckpt/arr",
                           sharding=NamedSharding(small, P("rows", "cols")))
    np.testing.assert_array_equal(np.asarray(elastic), np.asarray(a.data))
    # host-assembly convenience path too
    full = load_sharded("memory://marlin/ckpt/arr")
    np.testing.assert_array_equal(np.asarray(full), np.asarray(a.data))


def test_remote_pytree_checkpoint_roundtrip():
    from marlin_tpu.io.checkpoint import load_checkpoint, save_checkpoint

    state = {"w": jnp.arange(6.0).reshape(2, 3), "b": jnp.zeros((3,))}
    save_checkpoint(state, "memory://marlin/ckpt/train", step=7)
    save_checkpoint({"w": jnp.ones((2, 3)), "b": jnp.ones((3,))},
                    "memory://marlin/ckpt/train", step=9)
    restored, step = load_checkpoint(state, "memory://marlin/ckpt/train")
    assert step == 9
    np.testing.assert_array_equal(np.asarray(restored["w"]), np.ones((2, 3)))
    restored7, _ = load_checkpoint(state, "memory://marlin/ckpt/train", step=7)
    np.testing.assert_array_equal(np.asarray(restored7["w"]),
                                  np.arange(6.0).reshape(2, 3))


def test_file_scheme_checkpoint_roundtrip(mesh, tmp_path):
    """file:// URIs hit the local fast path with the scheme stripped — no
    junk './file:' trees (regression: ensure_dir/list_names/make_parent_dirs
    treated the scheme as part of the OS path)."""
    from marlin_tpu.io.checkpoint import load_sharded, save_sharded
    from marlin_tpu.io.text import load_matrix_file, save_matrix

    a = mt.BlockMatrix.random(4, 12, 8, mesh=mesh)
    uri = f"file://{tmp_path}/ck/arr"
    save_sharded(a.data, uri)
    assert (tmp_path / "ck" / "arr").is_dir()
    back = load_sharded(uri, sharding=a.data.sharding)
    np.testing.assert_array_equal(np.asarray(back), np.asarray(a.data))

    m = mt.DenseVecMatrix.from_array(np.eye(3, dtype=np.float32), mesh)
    save_matrix(m, f"file://{tmp_path}/sub/m.txt")
    assert (tmp_path / "sub" / "m.txt").is_file()
    np.testing.assert_allclose(
        load_matrix_file(f"file://{tmp_path}/sub/m.txt", mesh).to_numpy(),
        np.eye(3))
    assert not os.path.exists("file:"), "junk scheme-named dir created in cwd"


def test_file_uri_with_authority_rejected(tmp_path, mesh):
    """file://host/path has an authority component — must error, not resolve
    to the cwd-relative 'host/path' (ADVICE r3)."""
    from marlin_tpu.io.fs import local_path, open_path

    with pytest.raises(ValueError, match="authority"):
        local_path("file://somehost/data/m.txt")
    with pytest.raises(ValueError, match="authority"):
        open_path("file://somehost/data/m.txt")
    # empty authority (file:///abs) still works
    p = str(tmp_path / "ok.txt")
    with open_path(f"file://{p}", "w") as f:   # tmp_path is absolute
        f.write("x")
    assert open(p).read() == "x"


def test_byte_lru_bounds_shard_cache():
    from marlin_tpu.io.checkpoint import _ByteLRU

    lru = _ByteLRU(max_bytes=100)
    a = np.zeros(10, np.float32)  # 40 bytes
    b = np.ones(10, np.float32)
    c = np.full(10, 2.0, np.float32)
    lru.put("a", a); lru.put("b", b)
    assert lru.get("a") is a  # refreshes recency
    lru.put("c", c)           # 120 > 100: evicts LRU ("b")
    assert lru.get("b") is None
    assert lru.get("a") is a and lru.get("c") is c
    lru.put("huge", np.zeros(1000, np.float32))  # oversized: never cached
    assert lru.get("huge") is None
    assert lru.get("a") is a  # and it evicted nothing


def test_remote_restore_with_tiny_cache(mesh):
    """Correctness is cache-independent: a byte-bound smaller than one shard
    degrades to re-downloads, never to wrong data."""
    pytest.importorskip("fsspec")
    from marlin_tpu.io.checkpoint import load_sharded as ls, save_sharded as ss

    a = mt.DenseVecMatrix.random(5, 32, 16, mesh=mesh)
    ss(a.data, "memory://marlin/ckpt_tiny/arr")
    with mt.config_context(ckpt_cache_bytes=8):
        back = ls("memory://marlin/ckpt_tiny/arr", sharding=a.data.sharding)
    np.testing.assert_allclose(np.asarray(back), np.asarray(a.data))


def test_byte_lru_overwrite_accounting():
    from marlin_tpu.io.checkpoint import _ByteLRU

    lru = _ByteLRU(max_bytes=100)
    lru.put("a", np.zeros(10, np.float32))   # 40
    lru.put("a", np.ones(10, np.float32))    # overwrite: still 40 accounted
    assert lru._bytes == 40
    lru.put("b", np.zeros(10, np.float32))   # 80 total — no eviction needed
    assert lru.get("a") is not None and lru.get("b") is not None
