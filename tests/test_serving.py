"""Serving-engine suite: admission, bucketing, lifecycle, chaos, metrics.

The acceptance scenario (test_acceptance_continuous_batching) drives 36
concurrent requests across two shape buckets through :class:`ServeEngine` on
an injectable clock and asserts the subsystem's four contracts: exactly one
Result per request, outputs bit-identical to calling
:func:`lm_generate_batch` directly on the same bucket shape, deadline
expiry surfaced (never silently dropped), and a compile count bounded by the
bucket count. Everything runs greedy/seeded on the CPU mesh, so it is fully
deterministic.
"""

import threading
import types

import numpy as np
import pytest

import jax

from marlin_tpu.models import TransformerLM
from marlin_tpu.models.transformer import lm_generate_batch
from marlin_tpu.serving import (
    STATUS_ERROR,
    STATUS_EXPIRED,
    STATUS_OK,
    STATUS_REJECTED,
    STATUS_SHUTTING_DOWN,
    AdmissionQueue,
    BatchFormer,
    Request,
    ServeEngine,
    bucket_kv_bytes,
    normalize_buckets,
    percentile,
    pick_bucket,
)
from marlin_tpu.utils import EventLog, faults
from marlin_tpu.utils.faults import FaultInjected, RaiseFault, Schedule

HEADS = 2
BUCKETS = ((8, 4), (16, 4))


class FakeClock:
    """Deterministic engine clock: only advances when the test says so."""

    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


@pytest.fixture(scope="module")
def params():
    """One tiny LM for the whole module, so every engine shares the jit
    cache (compile-count assertions measure deltas, not absolutes)."""
    return TransformerLM(vocab=32, d_model=16, heads=HEADS, layers=2,
                         seed=9).init_params()


def _engine(params, **kw):
    kw.setdefault("buckets", BUCKETS)
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_wait_ms", 0.0)
    kw.setdefault("queue_depth", 64)
    return ServeEngine(params, HEADS, **kw)


def _reference(params, prompt, steps_req, bucket):
    """What the engine MUST produce for one request: lm_generate_batch called
    directly on the request's bucket shape (greedy, so batch composition and
    the PRNG key cannot change the row)."""
    p, s = bucket
    n = len(prompt)
    padded = np.zeros((1, p), np.int32)
    padded[0, :n] = prompt
    out = np.asarray(lm_generate_batch(
        params, padded, np.array([n], np.int32), jax.random.key(0),
        heads=HEADS, max_len=p + s, steps=s))
    return out[0, : n + steps_req]


# --------------------------------------------------------------- unit layer


def test_normalize_and_pick_bucket():
    assert normalize_buckets([(16, 4), (8, 4)]) == ((8, 4), (16, 4))
    assert pick_bucket(3, 4, BUCKETS) == (8, 4)
    assert pick_bucket(8, 4, BUCKETS) == (8, 4)     # exact fit, no pad
    assert pick_bucket(9, 4, BUCKETS) == (16, 4)
    assert pick_bucket(17, 4, BUCKETS) is None      # prompt too long
    assert pick_bucket(4, 5, BUCKETS) is None       # steps too deep
    with pytest.raises(ValueError, match="duplicate"):
        normalize_buckets([(8, 4), (8, 4)])
    with pytest.raises(ValueError, match="at least one"):
        normalize_buckets([])


def test_bucket_kv_bytes(params):
    # layers(2) x k&v(2) x max_len(8+4) x kv_heads(2) x dh(8) x f32(4)
    assert bucket_kv_bytes(params, HEADS, (8, 4)) == 2 * 2 * 12 * 2 * 8 * 4
    assert bucket_kv_bytes(params, HEADS, (8, 4), batch=4) == \
        4 * bucket_kv_bytes(params, HEADS, (8, 4))
    # bf16 halves the cache — the GQA/serving memory story end to end
    assert bucket_kv_bytes(params, HEADS, (8, 4), compute_dtype="bfloat16") \
        == bucket_kv_bytes(params, HEADS, (8, 4)) // 2


def test_admission_queue_bounds():
    q = AdmissionQueue(depth=2, budget_bytes=100)
    assert q.try_admit(60) is None
    assert "HBM" in q.try_admit(60)          # byte budget
    assert q.try_admit(30) is None
    assert "queue full" in q.try_admit(1)    # depth
    q.release(60)
    assert q.try_admit(1) is None
    q.close("draining")
    assert q.try_admit(1) == "draining"


def test_admission_queue_oversized_first_request():
    """A request dearer than the whole budget must still admit when the
    queue is empty — otherwise it deadlocks the engine forever."""
    q = AdmissionQueue(depth=4, budget_bytes=10)
    assert q.try_admit(1000) is None
    assert "HBM" in q.try_admit(1)


def _stub_entry(priority=0, enq_t=0.0, bucket=(8, 4)):
    r = types.SimpleNamespace(priority=priority, temperature=0.0,
                              top_p=None, top_k=None, seed=0)
    return types.SimpleNamespace(request=r, enq_t=enq_t, bucket=bucket)


def test_batch_former_wait_and_priority():
    f = BatchFormer(BUCKETS, max_batch=2, max_wait=1.0)
    f.add(_stub_entry(priority=0, enq_t=0.0))
    key, hint = f.next_batch(now=0.5)
    assert key is None and hint == pytest.approx(0.5)   # not ripe yet
    key, batch = f.next_batch(now=1.0)                  # max_wait reached
    assert key[0] == (8, 4) and len(batch) == 1
    # full batch dispatches immediately; higher priority rides first
    for pri in (1, 5, 3):
        f.add(_stub_entry(priority=pri, enq_t=2.0))
    key, batch = f.next_batch(now=2.0)
    assert [e.request.priority for e in batch] == [5, 3]
    # force (the drain path) flushes the unripe leftover
    key, batch = f.next_batch(now=2.0, force=True)
    assert [e.request.priority for e in batch] == [1]
    assert f.pending() == 0


def test_batch_former_groups_by_sampling_knobs():
    f = BatchFormer(BUCKETS, max_batch=4, max_wait=0.0)
    a, b = _stub_entry(), _stub_entry()
    b.request.temperature = 0.7
    f.add(a)
    f.add(b)
    key1, batch1 = f.next_batch(now=0.0)
    key2, batch2 = f.next_batch(now=0.0)
    assert len(batch1) == len(batch2) == 1
    assert {key1[1], key2[1]} == {0.0, 0.7}


def test_batch_former_sampled_requests_never_share_across_seeds():
    """A batch decodes under ONE PRNG key, so a sampled request must only
    ride with same-seed peers — different seeds sharing a batch would
    silently hand one request the other's stream. Greedy requests ignore
    the key: seeds must NOT fragment their batches."""
    f = BatchFormer(BUCKETS, max_batch=4, max_wait=0.0)
    for seed in (1, 2, 1):
        e = _stub_entry()
        e.request.temperature = 0.7
        e.request.seed = seed
        f.add(e)
    _, b1 = f.next_batch(now=0.0)
    _, b2 = f.next_batch(now=0.0)
    assert sorted(len(b) for b in (b1, b2)) == [1, 2]
    # greedy: different seeds, one batch
    for seed in (1, 2):
        e = _stub_entry()
        e.request.seed = seed
        f.add(e)
    _, b3 = f.next_batch(now=0.0)
    assert len(b3) == 2


# ------------------------------------------------------------- engine layer


def test_acceptance_continuous_batching(params):
    """The tentpole acceptance: >= 32 concurrent requests, >= 2 buckets,
    deterministic clock — exactly one Result each, bit-identical to the
    direct lm_generate_batch call, expired deadlines surfaced, drain()
    completes in-flight work, <= one compile per bucket."""
    clock = FakeClock()
    rng = np.random.default_rng(4)
    reqs = []
    for i in range(32):
        n = int(rng.integers(2, 17))            # both buckets exercised
        steps = int(rng.integers(1, 5))
        reqs.append(Request(prompt=rng.integers(0, 32, n).astype(np.int32),
                            steps=steps, seed=0))
    expired = [Request(prompt=[1, 2], steps=2, deadline=-1.0)
               for _ in range(4)]

    probe = getattr(lm_generate_batch, "_cache_size", None)
    before = probe() if probe else None

    eng = _engine(params, clock=clock)
    try:
        handles = {}
        lock = threading.Lock()

        def submit(chunk):
            for r in chunk:
                h = eng.submit(r)
                with lock:
                    handles[r.rid] = h

        threads = [threading.Thread(target=submit, args=(reqs[i::4],))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for r in expired:           # resolved synchronously, still a Result
            handles[r.rid] = eng.submit(r)

        results = {rid: h.result(timeout=120) for rid, h in handles.items()}
        # exactly one Result per request, none dropped
        assert len(results) == 36
        assert all(h.done() for h in handles.values())

        # deadline expiry is surfaced, not silently dropped
        for r in expired:
            assert results[r.rid].status == STATUS_EXPIRED
            assert "deadline" in results[r.rid].reason

        # compile count: at most one program per bucket (measured BEFORE the
        # direct-call references below add their own B=1 programs)
        if probe:
            assert probe() - before <= len(BUCKETS), \
                f"recompiled: {probe() - before} programs for {BUCKETS}"

        # bit-identical to the direct call on the same bucket shape
        for r in reqs:
            res = results[r.rid]
            assert res.status == STATUS_OK, (r.rid, res.reason)
            bucket = pick_bucket(len(r.prompt), r.steps, BUCKETS)
            ref = _reference(params, r.prompt, r.steps, bucket)
            assert res.tokens.tolist() == ref.tolist(), r.rid
            assert res.metrics["bucket"] == bucket
            assert res.metrics["total_s"] >= 0.0

        # drain() completes in-flight work (fresh wave, then drain)
        tail = [eng.submit(Request(prompt=[7, 8, 9], steps=2))
                for _ in range(3)]
        eng.drain()
        for h in tail:
            assert h.result(timeout=5).status == STATUS_OK
        assert eng.pending() == 0

        snap = eng.metrics.snapshot()
        assert snap["completed"] == 35 and snap["expired"] == 4
        assert snap["submitted"] == 35  # expired-at-submit never enqueued
    finally:
        eng.close()


def test_queue_full_rejects(params):
    eng = _engine(params, queue_depth=2, start=False)
    try:
        a = eng.submit(Request(prompt=[1], steps=1))
        b = eng.submit(Request(prompt=[2], steps=1))
        c = eng.submit(Request(prompt=[3], steps=1))
        assert not a.done() and not b.done()
        r = c.result(timeout=1)
        assert r.status == STATUS_REJECTED and "queue full" in r.reason
    finally:
        eng.close()
    assert a.result(timeout=1).status == STATUS_SHUTTING_DOWN


def test_hbm_budget_rejects(params):
    eng = _engine(params, hbm_budget_bytes=1, start=False)
    try:
        a = eng.submit(Request(prompt=[1], steps=1))   # first always admits
        b = eng.submit(Request(prompt=[2], steps=1))
        assert not a.done()
        r = b.result(timeout=1)
        assert r.status == STATUS_REJECTED and "HBM" in r.reason
    finally:
        eng.close()


def test_no_bucket_rejects(params):
    with _engine(params) as eng:
        r = eng.submit(Request(prompt=list(range(30)), steps=2)) \
            .result(timeout=1)
        assert r.status == STATUS_REJECTED and "no bucket" in r.reason
        r = eng.submit(Request(prompt=[1], steps=99)).result(timeout=1)
        assert r.status == STATUS_REJECTED and "no bucket" in r.reason


def test_deadline_expired_at_dispatch(params):
    """Requests admitted in time but dispatched late are retired expired —
    the retire-expired-rows half of the engine cycle."""
    clock = FakeClock()
    eng = _engine(params, clock=clock, start=False)
    try:
        stale = [eng.submit(Request(prompt=[1, 2], steps=2, deadline=5.0))
                 for _ in range(2)]
        fresh = eng.submit(Request(prompt=[1, 2], steps=2, deadline=1e9))
        clock.advance(10.0)          # past the stale deadlines, engine idle
        eng.start()
        for h in stale:
            r = h.result(timeout=30)
            assert r.status == STATUS_EXPIRED and "before dispatch" in r.reason
        assert fresh.result(timeout=30).status == STATUS_OK
    finally:
        eng.close()


def test_close_retires_queued_with_shutting_down(params):
    eng = _engine(params, start=False)
    handles = [eng.submit(Request(prompt=[i + 1], steps=1)) for i in range(3)]
    eng.close()
    for h in handles:
        r = h.result(timeout=1)
        assert r.status == STATUS_SHUTTING_DOWN and "closed" in r.reason
    assert eng.pending() == 0
    # close is terminal for admission too
    r = eng.submit(Request(prompt=[9], steps=1)).result(timeout=1)
    assert r.status == STATUS_REJECTED


def test_serve_step_fault_fails_batch_and_engine_recovers(params):
    """Chaos: a serve.step fault kills one batch mid-flight — its requests
    get error Results (never dropped), and the engine keeps serving."""
    with _engine(params) as eng:
        with faults.injected("serve.step", RaiseFault(times=1)):
            bad = eng.submit(Request(prompt=[1, 2], steps=2))
            r = bad.result(timeout=60)
            assert r.status == STATUS_ERROR and "FaultInjected" in r.reason
        good = eng.submit(Request(prompt=[1, 2], steps=2))
        assert good.result(timeout=60).status == STATUS_OK
        snap = eng.metrics.snapshot()
        assert snap["errors"] == 1 and snap["completed"] == 1
    assert eng.pending() == 0


def test_serve_enqueue_fault_propagates_to_caller(params):
    with _engine(params, start=False) as eng:
        with faults.injected("serve.enqueue", RaiseFault(times=1)):
            with pytest.raises(FaultInjected):
                eng.submit(Request(prompt=[1], steps=1))
        assert eng.pending() == 0      # nothing admitted by the failed call
        h = eng.submit(Request(prompt=[1], steps=1))
        assert eng.pending() == 1
        eng.drain()
        assert h.result(timeout=30).status == STATUS_OK


def test_metrics_eventlog_records(params, tmp_path):
    log = EventLog(str(tmp_path / "serve.jsonl"))
    with _engine(params, log=log) as eng:
        hs = [eng.submit(Request(prompt=[1, 2, 3], steps=2))
              for _ in range(3)]
        for h in hs:
            assert h.result(timeout=60).status == STATUS_OK
        eng.submit(Request(prompt=list(range(30)), steps=1)).result(timeout=1)
    recs = [r for r in log.read() if r["kind"] == "serve"]
    evs = [r["ev"] for r in recs]
    assert evs.count("enqueue") == 3 and evs.count("reject") == 1
    batches = [r for r in recs if r["ev"] == "batch"]
    assert batches and all(0.0 < b["occupancy"] <= 1.0 for b in batches)
    assert sum(b["rows"] for b in batches) == 3
    results = [r for r in recs if r["ev"] == "result" and r["status"] == "ok"]
    assert len(results) == 3
    for r in results:
        assert r["ttft_s"] == r["total_s"] >= r["queue_s"] >= 0.0


def test_sampling_knobs_partition_batches(params):
    """Different sampling knobs never share a batch; a traced temperature
    difference costs a second dispatch, not a second compile."""
    probe = getattr(lm_generate_batch, "_cache_size", None)
    eng = _engine(params, start=False)
    try:
        cold = [eng.submit(Request(prompt=[1, 2], steps=2))
                for _ in range(2)]
        hot = [eng.submit(Request(prompt=[1, 2], steps=2, temperature=0.7,
                                  seed=3)) for _ in range(2)]
        before = probe() if probe else None
        eng.start()
        eng.drain()
        for h in cold + hot:
            assert h.result(timeout=1).status == STATUS_OK
        assert eng.metrics.snapshot()["batches"] == 2
        # greedy rows are key/temperature-independent: cold rows must equal
        # the greedy reference even though a sampled group ran alongside
        ref = _reference(params, [1, 2], 2, (8, 4))
        for h in cold:
            assert h.result().tokens.tolist() == ref.tolist()
        if probe:
            assert probe() - before <= 1  # temperature is traced, not static
    finally:
        eng.close()


def test_priority_orders_dispatch(params, tmp_path):
    """Higher-priority requests claim slots first when a bucket queue is
    deeper than one batch."""
    log = EventLog(str(tmp_path / "serve.jsonl"))
    eng = _engine(params, log=log, start=False)
    try:
        low = [eng.submit(Request(prompt=[1, 2], steps=2, priority=0))
               for _ in range(4)]
        high = [eng.submit(Request(prompt=[3, 4], steps=2, priority=5))
                for _ in range(4)]
        eng.start()
        eng.drain()
        for h in low + high:
            assert h.result(timeout=1).status == STATUS_OK
    finally:
        eng.close()
    order = [r["rid"] for r in log.read()
             if r["kind"] == "serve" and r.get("ev") == "result"]
    high_rids = {h.request.rid for h in high}
    assert set(order[:4]) == high_rids, order


def test_warmup_then_traffic_compiles_nothing(params):
    """warmup() pays every bucket's compile up front; traffic afterwards
    (same greedy signature) adds zero programs."""
    probe = getattr(lm_generate_batch, "_cache_size", None)
    if probe is None:
        pytest.skip("jit cache probe unavailable on this JAX")
    with _engine(params) as eng:
        assert eng.warmup() == len(BUCKETS)
        before = probe()
        hs = [eng.submit(Request(prompt=[1] * n, steps=2))
              for n in (2, 5, 8, 12, 16)]
        for h in hs:
            assert h.result(timeout=60).status == STATUS_OK
        assert probe() == before, "serving traffic recompiled after warmup"


def test_drain_idempotent_and_usable_from_context(params):
    with _engine(params) as eng:
        h = eng.submit(Request(prompt=[5, 6], steps=2))
        eng.drain()
        assert h.result(timeout=1).status == STATUS_OK
        eng.drain()   # terminal + idempotent
        r = eng.submit(Request(prompt=[5], steps=1)).result(timeout=1)
        assert r.status == STATUS_REJECTED and "draining" in r.reason


def test_percentile_helper():
    xs = list(range(1, 101))
    assert percentile(xs, 50) == 50
    assert percentile(xs, 99) == 99
    assert percentile([7.0], 99) == 7.0
    with pytest.raises(ValueError):
        percentile([], 50)


def test_aot_compile_buckets_reports_hbm(params):
    """Compile-only TPU evidence for bucket sizing (needs libtpu)."""
    from marlin_tpu.serving import aot_compile_buckets
    from marlin_tpu.utils.aot import supports_aot_tpu

    if not supports_aot_tpu():
        pytest.skip("no libtpu: compile-only TPU topology unavailable")
    peaks = aot_compile_buckets(params, HEADS, [(8, 4)], max_batch=2)
    assert set(peaks) == {(8, 4)} and peaks[(8, 4)] > 0


@pytest.mark.slow
def test_serving_soak_with_chaos(params):
    """Multi-minute-class soak: concurrent submitters, probabilistic
    serve.step chaos, ragged sizes — every request resolves, counters add
    up, nothing leaks (conftest checks threads + fault registry)."""
    rng = np.random.default_rng(11)
    n_threads, per_thread = 4, 40
    eng = _engine(params, queue_depth=n_threads * per_thread)
    handles, lock = [], threading.Lock()

    def submitter(seed):
        r = np.random.default_rng(seed)
        for _ in range(per_thread):
            req = Request(prompt=r.integers(0, 32, int(r.integers(1, 17))),
                          steps=int(r.integers(1, 5)),
                          priority=int(r.integers(0, 3)))
            h = eng.submit(req)
            with lock:
                handles.append(h)

    try:
        with faults.injected(
                "serve.step",
                RaiseFault(times=-1, schedule=Schedule(seed=3, rate=0.05))):
            threads = [threading.Thread(target=submitter, args=(100 + i,))
                       for i in range(n_threads)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            eng.drain()
        statuses = [h.result(timeout=300).status for h in handles]
    finally:
        eng.close()
    assert len(statuses) == n_threads * per_thread
    assert set(statuses) <= {STATUS_OK, STATUS_ERROR}
    snap = eng.metrics.snapshot()
    assert snap["completed"] == statuses.count(STATUS_OK)
    assert snap["errors"] == statuses.count(STATUS_ERROR)
    assert snap["completed"] + snap["errors"] == len(statuses)
    assert eng.pending() == 0
