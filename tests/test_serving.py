"""Serving-engine suite: admission, bucketing, lifecycle, chaos, metrics.

The acceptance scenario (test_acceptance_continuous_batching) drives 36
concurrent requests across two shape buckets through :class:`ServeEngine` on
an injectable clock — under BOTH KV backends (the paged pool, the default,
and the dense slot slab, the PR 4 control) — and asserts the subsystem's
contracts: exactly one Result per request, per-row outputs bit-identical to
the direct :func:`lm_generate` call on the unpadded prompt (greedy decode
is composition-independent), deadline expiry surfaced (never silently
dropped), and a bounded compile count (≤ 2 programs per bucket slab, ≤ 3
paged — prefill-chunk + decode + the shared page-copy; the conftest
``compile_count`` fixture). Everything runs greedy/seeded on the CPU mesh,
so it is fully deterministic. Paged-pool internals (alloc/refcount/COW/
prefix cache/chunked-prefill resumability) live in tests/test_paging.py.
"""

import threading
import types

import numpy as np
import pytest

import jax

from marlin_tpu.models import TransformerLM
from marlin_tpu.models.transformer import (lm_decode_paged, lm_decode_rows,
                                           lm_generate, lm_prefill_paged,
                                           lm_prefill_slot)
from marlin_tpu.serving import (
    STATUS_ERROR,
    STATUS_EXPIRED,
    STATUS_OK,
    STATUS_REJECTED,
    STATUS_SHUTTING_DOWN,
    AdmissionQueue,
    BatchFormer,
    Request,
    ServeEngine,
    bucket_kv_bytes,
    normalize_buckets,
    percentile,
    pick_bucket,
)
from marlin_tpu.utils import EventLog, faults
from marlin_tpu.utils.faults import FaultInjected, RaiseFault, Schedule

HEADS = 2
BUCKETS = ((8, 4), (16, 4))
PAGE_LEN = 4  # small pages so every bucket is genuinely multi-page


class FakeClock:
    """Deterministic engine clock: only advances when the test says so."""

    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


@pytest.fixture(scope="module")
def params():
    """One tiny LM for the whole module, so every engine shares the jit
    cache (compile-count assertions measure deltas, not absolutes)."""
    return TransformerLM(vocab=32, d_model=16, heads=HEADS, layers=2,
                         seed=9).init_params()


def _engine(params, **kw):
    kw.setdefault("buckets", BUCKETS)
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_wait_ms", 0.0)
    kw.setdefault("queue_depth", 64)
    kw.setdefault("page_len", PAGE_LEN)
    # ample page capacity: these tests exercise the queue-depth / explicit
    # HBM gates, not pool sizing (test_paging.py covers page-unit admission
    # and the auto-sized pool)
    kw.setdefault("num_pages", 1024)
    return ServeEngine(params, HEADS, **kw)


def _reference_single(params, prompt, steps_req, heads=HEADS):
    """The row-level acceptance bar: lm_generate on the UNPADDED prompt at
    its own max_len — per-row greedy output must be bit-identical to it
    regardless of bucket padding, slab width, or co-resident rows."""
    prompt = np.asarray(prompt, np.int32)
    return np.asarray(lm_generate(
        params, prompt, jax.random.key(0), heads=heads,
        max_len=len(prompt) + steps_req, steps=steps_req))


# --------------------------------------------------------------- unit layer


def test_normalize_and_pick_bucket():
    assert normalize_buckets([(16, 4), (8, 4)]) == ((8, 4), (16, 4))
    assert pick_bucket(3, 4, BUCKETS) == (8, 4)
    assert pick_bucket(8, 4, BUCKETS) == (8, 4)     # exact fit, no pad
    assert pick_bucket(9, 4, BUCKETS) == (16, 4)
    assert pick_bucket(17, 4, BUCKETS) is None      # prompt too long
    assert pick_bucket(4, 5, BUCKETS) is None       # steps too deep
    with pytest.raises(ValueError, match="duplicate"):
        normalize_buckets([(8, 4), (8, 4)])
    with pytest.raises(ValueError, match="at least one"):
        normalize_buckets([])


def test_bucket_kv_bytes(params):
    # layers(2) x k&v(2) x max_len(8+4) x kv_heads(2) x dh(8) x f32(4)
    assert bucket_kv_bytes(params, HEADS, (8, 4)) == 2 * 2 * 12 * 2 * 8 * 4
    assert bucket_kv_bytes(params, HEADS, (8, 4), batch=4) == \
        4 * bucket_kv_bytes(params, HEADS, (8, 4))
    # bf16 halves the cache — the GQA/serving memory story end to end
    assert bucket_kv_bytes(params, HEADS, (8, 4), compute_dtype="bfloat16") \
        == bucket_kv_bytes(params, HEADS, (8, 4)) // 2


def test_admission_queue_bounds():
    q = AdmissionQueue(depth=2, budget_bytes=100)
    assert q.try_admit(60) is None
    assert "HBM" in q.try_admit(60)          # byte budget
    assert q.try_admit(30) is None
    assert "queue full" in q.try_admit(1)    # depth
    q.release(60)
    assert q.try_admit(1) is None
    q.close("draining")
    assert q.try_admit(1) == "draining"


def test_admission_queue_oversized_first_request():
    """A request dearer than the whole budget must still admit when the
    queue is empty — otherwise it deadlocks the engine forever."""
    q = AdmissionQueue(depth=4, budget_bytes=10)
    assert q.try_admit(1000) is None
    assert "HBM" in q.try_admit(1)


def _stub_entry(priority=0, enq_t=0.0, bucket=(8, 4)):
    r = types.SimpleNamespace(priority=priority, temperature=0.0,
                              top_p=None, top_k=None, seed=0)
    return types.SimpleNamespace(request=r, enq_t=enq_t, bucket=bucket)


def test_batch_former_priority_and_bucket_queues():
    """The post-gang former: one priority-ordered FIFO per bucket. Higher
    priority claims first, FIFO among equals, sampling knobs never
    partition (they are per-row traced in the decode programs)."""
    f = BatchFormer(BUCKETS, max_batch=2)
    for pri, seed, temp in ((0, 1, 0.0), (5, 2, 0.7), (3, 1, 0.9)):
        e = _stub_entry(priority=pri)
        e.request.seed = seed
        e.request.temperature = temp
        f.add(e)
    f.add(_stub_entry(priority=0, bucket=(16, 4)))
    assert f.pending() == 4
    assert f.pending_buckets() == {(8, 4), (16, 4)}
    taken = f.take_for_bucket((8, 4), 2)
    assert [e.request.priority for e in taken] == [5, 3]
    assert f.take_for_bucket((99, 99), 4) == []
    rest = f.take_all()
    assert {e.bucket for e in rest} == {(8, 4), (16, 4)}
    assert f.pending() == 0


def test_batch_former_fifo_among_equal_priority():
    f = BatchFormer(BUCKETS, max_batch=4)
    entries = [_stub_entry(priority=1) for _ in range(3)]
    for e in entries:
        f.add(e)
    assert f.take_for_bucket((8, 4), 3) == entries  # arrival order kept


# ------------------------------------------------------------- engine layer


@pytest.mark.parametrize("paged", [False, True], ids=["slab", "paged"])
def test_acceptance_continuous_batching(params, paged):
    """The tentpole acceptance: >= 32 concurrent requests, >= 2 buckets,
    deterministic clock — exactly one Result each, per-row bit-identical to
    the direct lm_generate call on the unpadded prompt (both KV backends:
    the paged pool and the dense-slab control), expired deadlines surfaced,
    drain() completes in-flight work, and a bounded compile count (<= 2
    programs per bucket slab; <= 2 per bucket paged plus the one shared
    page-copy program — <= 3 total per bucket, for any knob mix)."""
    clock = FakeClock()
    rng = np.random.default_rng(4)
    reqs = []
    for i in range(32):
        n = int(rng.integers(2, 17))            # both buckets exercised
        steps = int(rng.integers(1, 5))
        reqs.append(Request(prompt=rng.integers(0, 32, n).astype(np.int32),
                            steps=steps, seed=0))
    expired = [Request(prompt=[1, 2], steps=2, deadline=-1.0)
               for _ in range(4)]

    if paged:
        probes = [getattr(f, "_cache_size", None)
                  for f in (lm_prefill_paged, lm_decode_paged)]
        per_bucket = 2  # + the shared page-copy program, counted below
    else:
        probes = [getattr(f, "_cache_size", None)
                  for f in (lm_prefill_slot, lm_decode_rows)]
        per_bucket = 2
    probes = [p for p in probes if p is not None]
    before = sum(p() for p in probes)

    eng = _engine(params, clock=clock, paged=paged)
    try:
        handles = {}
        lock = threading.Lock()

        def submit(chunk):
            for r in chunk:
                h = eng.submit(r)
                with lock:
                    handles[r.rid] = h

        threads = [threading.Thread(target=submit, args=(reqs[i::4],))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for r in expired:           # resolved synchronously, still a Result
            handles[r.rid] = eng.submit(r)

        results = {rid: h.result(timeout=120) for rid, h in handles.items()}
        # exactly one Result per request, none dropped
        assert len(results) == 36
        assert all(h.done() for h in handles.values())

        # deadline expiry is surfaced, not silently dropped
        for r in expired:
            assert results[r.rid].status == STATUS_EXPIRED
            assert "deadline" in results[r.rid].reason

        # compile count: bounded by the bucket set (measured BEFORE the
        # direct-call references below add their own programs)
        if probes:
            grew = sum(p() for p in probes) - before
            assert grew <= per_bucket * len(BUCKETS), \
                f"recompiled: {grew} programs for {BUCKETS}"

        # per-row bit-identical to the direct call: lm_generate on the
        # unpadded prompt itself — regardless of bucket padding, page
        # boundaries, chunked prefill, or co-resident rows
        for r in reqs:
            res = results[r.rid]
            assert res.status == STATUS_OK, (r.rid, res.reason)
            bucket = pick_bucket(len(r.prompt), r.steps, BUCKETS)
            ref = _reference_single(params, r.prompt, r.steps)
            assert res.tokens.tolist() == ref.tolist(), r.rid
            assert res.metrics["bucket"] == bucket
            assert res.metrics["total_s"] >= 0.0
            assert res.metrics["ttft_s"] <= res.metrics["total_s"]

        # drain() completes in-flight work (fresh wave, then drain)
        tail = [eng.submit(Request(prompt=[7, 8, 9], steps=2))
                for _ in range(3)]
        eng.drain()
        for h in tail:
            assert h.result(timeout=5).status == STATUS_OK
        assert eng.pending() == 0

        snap = eng.metrics.snapshot()
        assert snap["completed"] == 35 and snap["expired"] == 4
        assert snap["submitted"] == 35  # expired-at-submit never enqueued
    finally:
        eng.close()


def test_queue_full_rejects(params):
    eng = _engine(params, queue_depth=2, start=False)
    try:
        a = eng.submit(Request(prompt=[1], steps=1))
        b = eng.submit(Request(prompt=[2], steps=1))
        c = eng.submit(Request(prompt=[3], steps=1))
        assert not a.done() and not b.done()
        r = c.result(timeout=1)
        assert r.status == STATUS_REJECTED and "queue full" in r.reason
    finally:
        eng.close()
    assert a.result(timeout=1).status == STATUS_SHUTTING_DOWN


def test_hbm_budget_rejects(params):
    eng = _engine(params, hbm_budget_bytes=1, start=False)
    try:
        a = eng.submit(Request(prompt=[1], steps=1))   # first always admits
        b = eng.submit(Request(prompt=[2], steps=1))
        assert not a.done()
        r = b.result(timeout=1)
        assert r.status == STATUS_REJECTED and "HBM" in r.reason
    finally:
        eng.close()


def test_no_bucket_rejects(params):
    with _engine(params) as eng:
        r = eng.submit(Request(prompt=list(range(30)), steps=2)) \
            .result(timeout=1)
        assert r.status == STATUS_REJECTED and "no bucket" in r.reason
        r = eng.submit(Request(prompt=[1], steps=99)).result(timeout=1)
        assert r.status == STATUS_REJECTED and "no bucket" in r.reason


def test_deadline_expired_at_dispatch(params):
    """Requests admitted in time but dispatched late are retired expired —
    the retire-expired-rows half of the engine cycle."""
    clock = FakeClock()
    eng = _engine(params, clock=clock, start=False)
    try:
        stale = [eng.submit(Request(prompt=[1, 2], steps=2, deadline=5.0))
                 for _ in range(2)]
        fresh = eng.submit(Request(prompt=[1, 2], steps=2, deadline=1e9))
        clock.advance(10.0)          # past the stale deadlines, engine idle
        eng.start()
        for h in stale:
            r = h.result(timeout=30)
            assert r.status == STATUS_EXPIRED and "before dispatch" in r.reason
        assert fresh.result(timeout=30).status == STATUS_OK
    finally:
        eng.close()


def test_close_retires_queued_with_shutting_down(params):
    eng = _engine(params, start=False)
    handles = [eng.submit(Request(prompt=[i + 1], steps=1)) for i in range(3)]
    eng.close()
    for h in handles:
        r = h.result(timeout=1)
        assert r.status == STATUS_SHUTTING_DOWN and "closed" in r.reason
    assert eng.pending() == 0
    # close is terminal for admission too: a deterministic shutting_down
    # Result, not a generic rejection (the router's failover signal)
    r = eng.submit(Request(prompt=[9], steps=1)).result(timeout=1)
    assert r.status == STATUS_SHUTTING_DOWN


def test_serve_step_fault_fails_request_and_engine_recovers(params):
    """Chaos: a serve.step fault kills one slab prefill mid-flight — the
    request gets an error Result (never dropped), and the engine keeps
    serving (the paged analog, serve.prefill, lives in test_paging.py)."""
    with _engine(params, paged=False) as eng:
        with faults.injected("serve.step", RaiseFault(times=1)):
            bad = eng.submit(Request(prompt=[1, 2], steps=2))
            r = bad.result(timeout=60)
            assert r.status == STATUS_ERROR and "FaultInjected" in r.reason
        good = eng.submit(Request(prompt=[1, 2], steps=2))
        assert good.result(timeout=60).status == STATUS_OK
        snap = eng.metrics.snapshot()
        assert snap["errors"] == 1 and snap["completed"] == 1
    assert eng.pending() == 0


def test_serve_enqueue_fault_propagates_to_caller(params):
    with _engine(params, start=False) as eng:
        with faults.injected("serve.enqueue", RaiseFault(times=1)):
            with pytest.raises(FaultInjected):
                eng.submit(Request(prompt=[1], steps=1))
        assert eng.pending() == 0      # nothing admitted by the failed call
        h = eng.submit(Request(prompt=[1], steps=1))
        assert eng.pending() == 1
        eng.drain()
        assert h.result(timeout=30).status == STATUS_OK


def test_metrics_eventlog_records(params, tmp_path):
    """Paged engine event stream: prefill/step/page records with occupancy
    and pool accounting."""
    log = EventLog(str(tmp_path / "serve.jsonl"))
    with _engine(params, log=log) as eng:
        hs = [eng.submit(Request(prompt=[1, 2, 3], steps=2))
              for _ in range(3)]
        for h in hs:
            assert h.result(timeout=60).status == STATUS_OK
        eng.submit(Request(prompt=list(range(30)), steps=1)).result(timeout=1)
    recs = [r for r in log.read() if r["kind"] == "serve"]
    evs = [r["ev"] for r in recs]
    assert evs.count("enqueue") == 3 and evs.count("reject") == 1
    steps = [r for r in recs if r["ev"] == "step"]
    assert steps and all(0.0 < s["occupancy"] <= 1.0 for s in steps)
    prefills = [r for r in recs if r["ev"] == "prefill"]
    assert len(prefills) == 3  # one chunk each (3-token prompts)
    assert all(p["chunk"][0] == 0 and p["chunk"][1] == 3 for p in prefills)
    assert sum(p["new_tokens"] for p in prefills) == 3  # final chunks only
    pages = [r for r in recs if r["ev"] == "page"]
    allocs = [r for r in pages if r["action"] == "alloc"]
    frees = [r for r in pages if r["action"] == "free"]
    assert len(allocs) == 3 and len(frees) == 3
    assert all(0 < r["used"] <= r["total"] for r in allocs)
    assert sum(r["pages"] for r in allocs) == sum(r["pages"] for r in frees)
    results = [r for r in recs if r["ev"] == "result" and r["status"] == "ok"]
    assert len(results) == 3
    for r in results:
        assert r["total_s"] >= r["ttft_s"] >= r["queue_s"] >= 0.0


def test_priority_orders_dispatch(params, tmp_path):
    """Higher-priority requests claim slots first when a bucket queue is
    deeper than one batch."""
    log = EventLog(str(tmp_path / "serve.jsonl"))
    eng = _engine(params, log=log, start=False)
    try:
        low = [eng.submit(Request(prompt=[1, 2], steps=2, priority=0))
               for _ in range(4)]
        high = [eng.submit(Request(prompt=[3, 4], steps=2, priority=5))
                for _ in range(4)]
        eng.start()
        eng.drain()
        for h in low + high:
            assert h.result(timeout=1).status == STATUS_OK
    finally:
        eng.close()
    order = [r["rid"] for r in log.read()
             if r["kind"] == "serve" and r.get("ev") == "result"]
    high_rids = {h.request.rid for h in high}
    assert set(order[:4]) == high_rids, order


@pytest.mark.parametrize("paged", [False, True], ids=["slab", "paged"])
def test_warmup_then_traffic_compiles_nothing(params, compile_count, paged):
    """warmup() pays every bucket's compile up front — the prefill/decode
    pair per bucket (plus the shared page-copy program paged) — and
    traffic afterwards adds ZERO XLA compiles (the promoted compile-bound
    guard from tests/conftest.py)."""
    with _engine(params, paged=paged) as eng:
        assert eng.warmup() == len(BUCKETS)
        with compile_count() as c:
            hs = [eng.submit(Request(prompt=[1] * n, steps=2))
                  for n in (2, 5, 8, 12, 16)]
            for h in hs:
                assert h.result(timeout=60).status == STATUS_OK
        assert c.count == 0, \
            f"serving traffic recompiled after warmup ({c.count} compiles)"


def test_drain_idempotent_and_usable_from_context(params):
    with _engine(params) as eng:
        h = eng.submit(Request(prompt=[5, 6], steps=2))
        eng.drain()
        assert h.result(timeout=1).status == STATUS_OK
        eng.drain()   # terminal + idempotent
        r = eng.submit(Request(prompt=[5], steps=1)).result(timeout=1)
        assert r.status == STATUS_SHUTTING_DOWN and "draining" in r.reason


def test_drain_vs_concurrent_submit_race(params):
    """Regression (satellite): submits racing a drain must each get a
    deterministic terminal Result — completed if they made it in,
    ``shutting_down`` if they arrived after the gate shut — NEVER a
    silently-dropped request. Four submitter threads hammer while the main
    thread drains mid-burst."""
    eng = _engine(params, queue_depth=4096)
    handles, lock = [], threading.Lock()
    go = threading.Event()

    def submitter(seed):
        go.wait()
        for i in range(25):
            h = eng.submit(Request(prompt=[1 + (seed + i) % 8], steps=1))
            with lock:
                handles.append(h)

    threads = [threading.Thread(target=submitter, args=(i,))
               for i in range(4)]
    try:
        for t in threads:
            t.start()
        go.set()
        eng.drain()   # races the submitters by construction
        for t in threads:
            t.join()
        assert len(handles) == 100
        statuses = [h.result(timeout=60).status for h in handles]
    finally:
        eng.close()
    # every handle terminal, only the two deterministic outcomes
    assert set(statuses) <= {STATUS_OK, STATUS_SHUTTING_DOWN}, set(statuses)
    snap = eng.metrics.snapshot()
    assert snap["completed"] == statuses.count(STATUS_OK)
    assert eng.pending() == 0
    assert eng._queue.bytes_in_flight == 0


# ------------------------- row-level scheduling (paged default backend)


def test_rowlevel_step_events_and_slot_refill(params, tmp_path):
    """The row-level guarantee the gang loop cannot give: a finished row's
    slot is refilled ON THE NEXT STEP. Asserted via the per-step occupancy
    event stream (no sleeps): 3 requests through a 2-slot pool complete in
    2 full-occupancy decode steps — only possible if the slot freed by the
    short row hosts the queued row immediately."""
    log = EventLog(str(tmp_path / "serve.jsonl"))
    eng = _engine(params, max_batch=2, log=log, start=False)
    try:
        a = eng.submit(Request(prompt=[1, 2, 3], steps=3))
        b = eng.submit(Request(prompt=[4, 5], steps=2))
        c = eng.submit(Request(prompt=[6, 7], steps=2))
        eng.start()
        eng.drain()
        for h in (a, b, c):
            assert h.result(timeout=60).status == STATUS_OK
    finally:
        eng.close()
    # b (slots with a) finishes on step 1; c takes its slot before step 2
    assert b.result().metrics["slot"] == c.result().metrics["slot"]
    steps = [r for r in log.read()
             if r["kind"] == "serve" and r.get("ev") == "step"]
    assert [s["rows"] for s in steps] == [2, 2]
    assert all(s["occupancy"] == 1.0 and s["new_tokens"] == s["rows"]
               and s["seconds"] >= 0.0 for s in steps)
    snap = eng.metrics.snapshot()
    assert snap["steps"] == 2 and snap["occupancy_mean"] == 1.0
    results = [r for r in log.read()
               if r["kind"] == "serve" and r.get("ev") == "result"]
    for r in results:
        assert r["ttft_s"] <= r["total_s"]


def test_rowlevel_eos_early_retirement_and_refill(params):
    """A row that emits its eos token retires early (fewer than ``steps``
    generated tokens, ending in the eos) and its slot frees for queued
    work; an eos VALUE sitting in the prompt or pad region never stops a
    row (detection looks only at generated tokens)."""
    prompt = [5, 3]
    ref = _reference_single(params, prompt, 4)
    gen = ref[len(prompt):].tolist()
    eos = gen[1]  # the second generated token
    with _engine(params, max_batch=1) as eng:
        h = eng.submit(Request(prompt=prompt, steps=4, eos=eos))
        # the 1-slot pool forces serial occupancy: tail only runs after h
        tail = eng.submit(Request(prompt=[9, 8], steps=2))
        res = h.result(timeout=60)
        assert res.status == STATUS_OK
        stop = gen.index(eos) + 1  # first eos emission wins
        assert res.tokens.tolist() == ref[: len(prompt) + stop].tolist()
        assert res.tokens[-1] == eos
        assert tail.result(timeout=60).status == STATUS_OK
        # pad-region immunity: prompt shorter than the bucket pads with 0s
        # and an eos VALUE may sit in prompt or pad — use an eos the greedy
        # continuation never emits (prompt token 5 unless generated too);
        # the row must run its full step budget
        unseen = next(v for v in range(32) if v not in gen)
        h2 = eng.submit(Request(prompt=prompt, steps=4, eos=unseen))
        res2 = h2.result(timeout=60)
    assert res2.status == STATUS_OK
    assert res2.tokens.tolist() == ref.tolist()


def test_rowlevel_mixed_sampling_knobs_share_steps(params, compile_count):
    """Per-row traced sampling knobs: a greedy row, a sampled row, and a
    top-p/top-k row share the same decode steps (no knob partitioning, no
    extra programs) and the greedy row stays bit-identical to
    lm_generate."""
    with _engine(params, max_batch=4) as eng:
        eng.warmup()
        with compile_count() as c:
            cold = eng.submit(Request(prompt=[1, 2], steps=3))
            hot = eng.submit(Request(prompt=[1, 2], steps=3,
                                     temperature=0.9, seed=7))
            nucl = eng.submit(Request(prompt=[3, 4], steps=3,
                                      temperature=0.8, top_p=0.9, top_k=5,
                                      seed=11))
            rs = [h.result(timeout=60) for h in (cold, hot, nucl)]
        assert c.count == 0, f"{c.count} compiles for a mixed-knob step"
        assert all(r.status == STATUS_OK for r in rs)
        assert rs[0].tokens.tolist() == \
            _reference_single(params, [1, 2], 3).tolist()
        for r in rs[1:]:
            assert r.tokens.size == 2 + 3
            assert np.all(r.tokens < 32) and np.all(r.tokens >= 0)
        snap = eng.metrics.snapshot()
        assert snap["batches"] == 0  # nothing gang-dispatched


def test_rowlevel_sampled_replay_is_composition_independent(params):
    """fold_in(key(seed), step) per row: the same sampled request replays
    the same tokens whether it rides alone or beside other rows."""
    req = dict(prompt=[2, 4, 6], steps=4, temperature=0.7, seed=13)
    with _engine(params, max_batch=4) as eng:
        alone = eng.submit(Request(**req)).result(timeout=60)
    with _engine(params, max_batch=4, start=False) as eng:
        crowd = [eng.submit(Request(prompt=[1] * n, steps=3))
                 for n in (2, 7, 3)]
        again = eng.submit(Request(**req))
        eng.start()
        eng.drain()
        assert all(h.result(timeout=60).status == STATUS_OK for h in crowd)
        again = again.result(timeout=60)
    assert alone.status == again.status == STATUS_OK
    assert alone.tokens.tolist() == again.tokens.tolist()


def test_rowlevel_gqa_bit_identical(params):
    """GQA (kv_heads < heads) through the row-level engine: the slab shape
    derives kv_heads from the params, ragged lengths decode from their own
    positions, and per-row output stays bit-identical to lm_generate."""
    gqa = TransformerLM(vocab=32, d_model=16, heads=4, layers=2, kv_heads=2,
                        seed=3).init_params()
    rng = np.random.default_rng(8)
    with ServeEngine(gqa, 4, buckets=BUCKETS, max_batch=4, max_wait_ms=0.0,
                     queue_depth=64) as eng:
        reqs = [Request(prompt=rng.integers(0, 32, int(rng.integers(2, 17)))
                        .astype(np.int32), steps=int(rng.integers(1, 5)))
                for _ in range(8)]
        hs = [eng.submit(r) for r in reqs]
        for r, h in zip(reqs, hs):
            res = h.result(timeout=120)
            assert res.status == STATUS_OK, res.reason
            ref = _reference_single(gqa, r.prompt, r.steps, heads=4)
            assert res.tokens.tolist() == ref.tolist()


def test_rowlevel_decode_step_fault_fails_only_live_rows(params):
    """Chaos: a serve.decode_step fault fails ONLY that step's live rows
    with error Results; queued requests still serve afterwards and the slot
    pool stays consistent (all slots free, budget fully released)."""
    eng = _engine(params, max_batch=2, start=False)
    try:
        live = [eng.submit(Request(prompt=[1, 2], steps=3))
                for _ in range(2)]
        queued = eng.submit(Request(prompt=[3, 4], steps=2))
        with faults.injected("serve.decode_step", RaiseFault(times=1)):
            eng.start()
            for h in live:
                r = h.result(timeout=60)
                assert r.status == STATUS_ERROR, r.status
                assert "FaultInjected" in r.reason
            assert queued.result(timeout=60).status == STATUS_OK
        after = eng.submit(Request(prompt=[5], steps=2))
        assert after.result(timeout=60).status == STATUS_OK
        snap = eng.metrics.snapshot()
        assert snap["errors"] == 2 and snap["completed"] == 2
    finally:
        eng.close()
    assert eng.pending() == 0
    assert eng._queue.bytes_in_flight == 0


@pytest.mark.parametrize("paged", [False, True], ids=["slab", "paged"])
def test_expiring_burst_releases_admission_budget(params, paged):
    """Regression (admission accounting): a burst of requests that all
    expire — some at submit, some at dispatch — must release every byte of
    the in-flight KV budget on retirement, or admission wedges forever.
    The paged leg charges in PAGE units (request_pages x kv_page_bytes) —
    the page-reservation mirror of the PR 4 byte-unit regression."""
    clock = FakeClock()
    if paged:
        from marlin_tpu.models.planner import kv_page_bytes, request_pages

        unit = (request_pages(2, 2, PAGE_LEN)
                * kv_page_bytes(params, HEADS, PAGE_LEN))
    else:
        unit = bucket_kv_bytes(params, HEADS, (8, 4))
    eng = _engine(params, clock=clock, start=False, paged=paged,
                  hbm_budget_bytes=10 * unit)
    try:
        at_submit = [eng.submit(Request(prompt=[1, 2], steps=2,
                                        deadline=-1.0)) for _ in range(3)]
        at_dispatch = [eng.submit(Request(prompt=[1, 2], steps=2,
                                          deadline=5.0)) for _ in range(6)]
        assert eng._queue.bytes_in_flight > 0
        clock.advance(10.0)
        eng.start()
        for h in at_submit + at_dispatch:
            r = h.result(timeout=60)
            assert r.status == STATUS_EXPIRED
        deadline = 50  # the worker releases asynchronously after _set
        import time as _t
        while eng._queue.bytes_in_flight and deadline:
            _t.sleep(0.01)
            deadline -= 1
        assert eng._queue.bytes_in_flight == 0
        assert eng.pending() == 0
        # admission is not wedged: a fresh request admits and completes
        ok = eng.submit(Request(prompt=[1, 2], steps=2))
        eng.drain()
        assert ok.result(timeout=60).status == STATUS_OK
    finally:
        eng.close()
    assert eng._queue.bytes_in_flight == 0


def test_crash_retry_releases_admission_budget_exactly_once(params):
    """Regression (admission accounting, the retry half of the expiring-
    burst guarantee): a request parked between attempts must hold EXACTLY
    its one admission reservation — never double-charged by the re-queue,
    and fully released on its final retirement whichever attempt serves
    it. Covers the decode-fault retry and the exhausted-budget error.
    Runs the (default) paged backend, so the reservation under test is the
    page-unit charge carried across attempts."""
    from marlin_tpu.models.planner import kv_page_bytes, request_pages

    cost = (request_pages(2, 3, PAGE_LEN)
            * kv_page_bytes(params, HEADS, PAGE_LEN))
    eng = _engine(params, max_batch=2, start=False,
                  hbm_budget_bytes=10 * cost)
    try:
        eng.warmup()
        hs = [eng.submit(Request(prompt=[1, 2], steps=3, max_attempts=2))
              for _ in range(2)]
        assert eng._queue.bytes_in_flight == 2 * cost
        with faults.injected("serve.decode_step", RaiseFault(times=1)):
            eng.start()
            for h in hs:
                r = h.result(timeout=60)
                assert r.status == STATUS_OK, (r.status, r.reason)
                assert r.metrics["attempt"] == 2
        snap = eng.metrics.snapshot()
        assert snap["retries"] == 2 and snap["completed"] == 2
        # exhausting the budget errors exactly once, still one release
        with faults.injected("serve.decode_step", RaiseFault(times=2)):
            bad = eng.submit(Request(prompt=[3, 4], steps=3, max_attempts=2))
            r = bad.result(timeout=60)
            assert r.status == STATUS_ERROR and "FaultInjected" in r.reason
        deadline = 200   # worker releases asynchronously after _set
        import time as _t
        while eng._queue.bytes_in_flight and deadline:
            _t.sleep(0.01)
            deadline -= 1
        assert eng._queue.bytes_in_flight == 0
        assert eng.pending() == 0
    finally:
        eng.close()
    assert eng._queue.bytes_in_flight == 0


def test_percentile_helper():
    xs = list(range(1, 101))
    assert percentile(xs, 50) == 50
    assert percentile(xs, 99) == 99
    assert percentile([7.0], 99) == 7.0
    with pytest.raises(ValueError):
        percentile([], 50)


def test_aot_compile_buckets_reports_hbm(params):
    """Compile-only TPU evidence for bucket sizing (needs libtpu)."""
    from marlin_tpu.serving import aot_compile_buckets
    from marlin_tpu.utils.aot import supports_aot_tpu

    if not supports_aot_tpu():
        pytest.skip("no libtpu: compile-only TPU topology unavailable")
    # this tiny model's compiler peak (weights + workspace) dwarfs its KV
    # slab arithmetic, so the planner-honesty warning MUST fire here — the
    # same signal that catches a real under-budgeted serve_max_batch
    with pytest.warns(RuntimeWarning, match="measured peak"):
        peaks = aot_compile_buckets(params, HEADS, [(8, 4)], max_batch=2)
    assert set(peaks) == {(8, 4)} and peaks[(8, 4)] > 0


@pytest.mark.slow
def test_serving_soak_with_chaos(params):
    """Multi-minute-class soak: concurrent submitters, probabilistic
    prefill + decode-step chaos, ragged sizes — every request resolves,
    counters add up, nothing leaks (conftest checks threads + fault
    registry)."""
    rng = np.random.default_rng(11)
    n_threads, per_thread = 4, 40
    eng = _engine(params, queue_depth=n_threads * per_thread)
    handles, lock = [], threading.Lock()

    def submitter(seed):
        r = np.random.default_rng(seed)
        for _ in range(per_thread):
            req = Request(prompt=r.integers(0, 32, int(r.integers(1, 17))),
                          steps=int(r.integers(1, 5)),
                          priority=int(r.integers(0, 3)))
            h = eng.submit(req)
            with lock:
                handles.append(h)

    try:
        with faults.injected(
                "serve.prefill",
                RaiseFault(times=-1, schedule=Schedule(seed=3, rate=0.05))), \
             faults.injected(
                "serve.decode_step",
                RaiseFault(times=-1, schedule=Schedule(seed=4, rate=0.02))):
            threads = [threading.Thread(target=submitter, args=(100 + i,))
                       for i in range(n_threads)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            eng.drain()
        statuses = [h.result(timeout=300).status for h in handles]
    finally:
        eng.close()
    assert len(statuses) == n_threads * per_thread
    assert set(statuses) <= {STATUS_OK, STATUS_ERROR}
    snap = eng.metrics.snapshot()
    assert snap["completed"] == statuses.count(STATUS_OK)
    assert snap["errors"] == statuses.count(STATUS_ERROR)
    assert snap["completed"] + snap["errors"] == len(statuses)
    assert eng.pending() == 0
