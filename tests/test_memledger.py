"""HBM-ledger suite (obs/memledger.py; docs/observability.md "Memory
attribution").

The acceptance bars, bottom up:

- **Exact accounting**: register/free debit exactly once, flow entries
  clamp at zero, transfer moves ownership without moving the process
  total, and every anomaly shape (double register, strict free of an
  unknown name, a flow driven negative, an unknown component) lands in
  the audit as an error — with the total still exact.
- **Reconciler edge cases**: a backend with no ``memory_stats`` renders
  "n/a", NEVER zero (zero reads as "nothing resident", the opposite of
  "unknown"); a pool rebuild that frees-then-registers the same name
  does not double-count; a negative unattributed remainder is reported,
  not clamped.
- **Concurrency**: 8 threads of register/free against a concurrent
  gauge render keep the audit exact (the scrape-stress bar from the
  module docstring).
- **Alarms**: the leak detector arms only above ``min_bytes`` with a
  live baseline, resolves on a real live-byte drop, and alerts after
  ``windows`` samples; OOM forensics dump one parseable JSONL artifact,
  rate-limited and pruned to the newest 16.
- **Calibration**: admission_ratio prefers a live ProgramCosts peak,
  falls back to the AOT table, clamps to [1, 32], caches per key, and a
  toy CPU model calibrates at exactly 1.0 — pre-ledger admission.
"""

import json
import os
import threading

import pytest

from marlin_tpu.config import config_context
from marlin_tpu.obs import memledger
from marlin_tpu.obs.console import render as console_render
from marlin_tpu.obs.memledger import (
    KNOWN_COMPONENTS,
    LeakDetector,
    MemoryLedger,
    admission_ratio,
    dump_oom_forensics,
    emit_snapshot,
    install_memledger_gauges,
    is_oom_error,
    memory_payload,
    ratio_table,
    reconcile,
)
from marlin_tpu.obs.metrics import MetricsRegistry
from marlin_tpu.obs.report import _memory_attribution_section, load_events
from marlin_tpu.utils.tracing import EventLog, set_default_event_log

HEADS = 2
BUCKETS = ((8, 8), (16, 8))
PAGE_LEN = 4

# install_memledger_gauges is idempotent per id(registry); pin every test
# registry for the module's lifetime so CPython can never hand a later
# test a recycled id (which would silently skip the install)
_PINNED: list = []


def _fresh_registry() -> MetricsRegistry:
    reg = MetricsRegistry()
    _PINNED.append(reg)
    return reg


@pytest.fixture(autouse=True)
def _clean_ledger():
    # the process ledger is a singleton; these tests deliberately seed
    # anomalies, so isolate every test (and leave the ledger clean for
    # the migration/fleet suites' audit assertions in the same process)
    memledger.reset_ledger()
    yield
    memledger.reset_ledger()


@pytest.fixture()
def default_log(tmp_path):
    log = EventLog(str(tmp_path / "events.jsonl"))
    prev = set_default_event_log(log)
    yield log
    set_default_event_log(prev)
    log.close()


@pytest.fixture(scope="module")
def params():
    from marlin_tpu.models import TransformerLM

    return TransformerLM(vocab=32, d_model=16, heads=HEADS, layers=2,
                         seed=9).init_params()


# --------------------------------------------------------- exact accounting


def test_register_free_exact():
    led = MemoryLedger()
    led.register("kvpool:a", 1000, "kvpool", owner="e1")
    led.register("program:als", 500, "program", owner="e1")
    assert led.total_bytes() == 1500
    assert led.totals() == {"kvpool": 1000, "program": 500}
    assert led.owner_bytes("e1") == 1500
    assert led.free("kvpool:a") == 1000
    assert led.total_bytes() == 500
    audit = led.audit()
    assert audit["ok"], audit["errors"]
    assert audit["registered_bytes"] == 500
    assert audit["entries"] == 1


def test_entries_sorted_and_shaped():
    led = MemoryLedger()
    led.register("b", 2, "kvpool", owner="y")
    led.register("a", 1, "program", owner="x")
    es = led.entries()
    assert [e["name"] for e in es] == ["a", "b"]
    assert es[0] == {"name": "a", "component": "program", "bytes": 1,
                     "owner": "x"}


def test_double_register_is_anomaly_but_total_stays_exact():
    led = MemoryLedger()
    led.register("x", 100, "kvpool")
    led.register("x", 250, "kvpool")  # replaced, not summed
    assert led.total_bytes() == 250
    audit = led.audit()
    assert not audit["ok"]
    assert any("double register" in e for e in audit["errors"])
    # the total invariant held through the anomaly
    assert audit["registered_bytes"] == 250


def test_strict_free_of_unknown_is_anomaly_lenient_is_noop():
    led = MemoryLedger()
    assert led.free("ghost", strict=False) == 0
    assert led.audit()["ok"]
    assert led.free("ghost") == 0
    audit = led.audit()
    assert not audit["ok"]
    assert any("not registered" in e for e in audit["errors"])


def test_negative_register_and_unknown_component_are_anomalies():
    led = MemoryLedger()
    led.register("neg", -64, "kvpool")
    led.register("odd", 10, "bogus")
    assert led.total_bytes() == 10  # negative clamped to 0
    audit = led.audit()
    assert not audit["ok"]
    assert any("negative size" in e for e in audit["errors"])
    assert any("unknown component" in e for e in audit["errors"])


def test_flow_entries_clamp_and_stay_registered_at_zero():
    led = MemoryLedger()
    led.add("prefetch:inflight", 300, "prefetch")
    led.add("prefetch:inflight", -100, "prefetch")
    assert led.total_bytes() == 200
    led.add("prefetch:inflight", -200, "prefetch")
    assert led.total_bytes() == 0
    # a drained flow is a live series at zero, not a freed slab
    assert led.audit()["entries"] == 1
    assert led.audit()["ok"]
    led.add("prefetch:inflight", -50, "prefetch")  # driven negative
    assert led.total_bytes() == 0
    audit = led.audit()
    assert not audit["ok"]
    assert any("driven" in e for e in audit["errors"])


def test_transfer_moves_owner_not_total():
    led = MemoryLedger()
    led.register("mig:1", 4096, "migration", owner="src")
    assert led.transfer("mig:1", "dst")
    assert led.owner_bytes("src") == 0
    assert led.owner_bytes("dst") == 4096
    assert led.total_bytes() == 4096
    # a second transfer of a consumed name is idempotent, not an anomaly
    led.free("mig:1")
    assert led.transfer("mig:1", "elsewhere") is False
    assert led.audit()["ok"]


def test_free_owner_sweeps_everything_the_owner_holds():
    led = MemoryLedger()
    led.register("kvpool:a", 100, "kvpool", owner="e1")
    led.register("mig:a", 50, "migration", owner="e1")
    led.register("kvpool:b", 70, "kvpool", owner="e2")
    assert led.free_owner("e1") == 150
    assert led.total_bytes() == 70
    assert led.owner_bytes("e1") == 0
    assert led.audit()["ok"]


def test_free_listener_fires_once_per_debit_and_swallows_errors():
    led = MemoryLedger()
    calls = []

    def listener(component, nbytes):
        calls.append((component, nbytes))
        raise RuntimeError("listener bug must not break the free")

    led.add_free_listener(listener)
    led.add_free_listener(listener)  # idempotent per callable
    led.register("x", 123, "kvpool")
    assert led.free("x") == 123
    assert calls == [("kvpool", 123)]
    led.add("f", 100, "prefetch")
    led.add("f", -40, "prefetch")  # flow debits feed the listener too
    assert calls == [("kvpool", 123), ("prefetch", 40)]


# ------------------------------------------------------ reconciler edge cases


def test_reconcile_without_live_view_is_na_not_zero(monkeypatch):
    monkeypatch.setattr(memledger, "live_device_bytes", lambda: None)
    memledger.get_ledger().register("kvpool:x", 512, "kvpool")
    rec = reconcile()
    assert rec["registered_bytes"] == 512
    assert rec["live_bytes"] is None
    assert rec["unattributed_bytes"] is None
    assert rec["unattributed_frac"] is None
    status, body = memory_payload()
    assert status == 200 and body["status"] == "ok"
    # "n/a", NEVER 0 — zero would read as "nothing resident"
    assert body["live_bytes"] == "n/a"
    assert body["unattributed_bytes"] == "n/a"
    assert body["unattributed_frac"] == "n/a"


def test_reconcile_with_live_view(monkeypatch):
    monkeypatch.setattr(memledger, "live_device_bytes", lambda: 1000)
    memledger.get_ledger().register("kvpool:x", 600, "kvpool")
    rec = reconcile()
    assert rec["live_bytes"] == 1000
    assert rec["unattributed_bytes"] == 400
    assert rec["unattributed_frac"] == 0.4


def test_reconcile_overcount_reported_not_clamped(monkeypatch):
    # ledger above live = the ledger over-counts; that asymmetry is the
    # finding, so the signed remainder must survive
    monkeypatch.setattr(memledger, "live_device_bytes", lambda: 500)
    memledger.get_ledger().register("kvpool:x", 600, "kvpool")
    rec = reconcile()
    assert rec["unattributed_bytes"] == -100
    assert rec["unattributed_frac"] == 0.0


def test_pool_rebuild_free_then_register_no_double_count():
    # the engine's _ensure_kvpool idiom: recover tears the slab down and
    # rebuilds under the SAME ledger name — free-then-register keeps the
    # account exact with zero anomalies, unlike a bare re-register
    led = memledger.get_ledger()
    for rebuild, nbytes in enumerate((1 << 20, 2 << 20, 1 << 19)):
        led.free("kvpool:eng", strict=False)
        led.register("kvpool:eng", nbytes, "kvpool", owner="eng")
        assert led.total_bytes() == nbytes, rebuild
    audit = led.audit()
    assert audit["ok"], audit["errors"]
    assert audit["entries"] == 1


def test_concurrent_register_free_under_scrape_stress(monkeypatch):
    # 8 writer threads vs a continuous render of the memledger collector:
    # every op atomic, the final audit exact, no render ever raises
    monkeypatch.setattr(memledger, "live_device_bytes", lambda: 1 << 30)
    led = memledger.get_ledger()
    reg = _fresh_registry()
    install_memledger_gauges(reg)
    stop = threading.Event()
    errors = []

    def writer(tid):
        try:
            for i in range(300):
                name = f"t{tid}:{i}"
                led.register(name, 4096 + tid, "kvpool", owner=f"th{tid}")
                led.add(f"flow{tid}", 128, "prefetch")
                led.add(f"flow{tid}", -128, "prefetch")
                led.free(name)
        except Exception as e:  # pragma: no cover - the failure we hunt
            errors.append(e)

    def scraper():
        while not stop.is_set():
            text = reg.render()
            assert "# TYPE marlin_mem_registered_bytes gauge" in text

    threads = [threading.Thread(target=writer, args=(t,)) for t in range(8)]
    s = threading.Thread(target=scraper)
    s.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    s.join()
    assert not errors, errors
    audit = led.audit()
    assert audit["ok"], audit["errors"]
    assert led.total_bytes() == 0  # flows drained to zero, slabs freed


# ------------------------------------------------------------ gauge families


def test_gauges_render_all_three_families(monkeypatch):
    monkeypatch.setattr(memledger, "live_device_bytes", lambda: 5000)
    led = memledger.get_ledger()
    led.register("kvpool:x", 3000, "kvpool")
    reg = _fresh_registry()
    install_memledger_gauges(reg)
    install_memledger_gauges(reg)  # idempotent per registry
    text = reg.render()
    for fam in ("marlin_mem_registered_bytes", "marlin_mem_live_bytes",
                "marlin_mem_unattributed_bytes"):
        assert f"# TYPE {fam} gauge" in text
    assert 'marlin_mem_registered_bytes{component="kvpool"} 3000' in text
    assert 'marlin_mem_registered_bytes{component="total"} 3000' in text
    # every known component exports a series even at zero
    for comp in KNOWN_COMPONENTS:
        assert f'component="{comp}"' in text
    assert 'marlin_mem_live_bytes{component="total"} 5000' in text
    assert 'marlin_mem_unattributed_bytes{component="total"} 2000' in text
    # each scrape doubles as a leak-detector observation window
    assert memledger.get_leak_detector()._last_live == 5000


def test_gauges_without_live_view_omit_live_samples(monkeypatch):
    monkeypatch.setattr(memledger, "live_device_bytes", lambda: None)
    reg = _fresh_registry()
    install_memledger_gauges(reg)
    text = reg.render()
    # the families exist (scrapers see the TYPE line) but carry no bogus
    # zero samples when the backend has no live view
    assert "# TYPE marlin_mem_live_bytes gauge" in text
    assert 'marlin_mem_live_bytes{component="total"}' not in text
    assert 'marlin_mem_unattributed_bytes{component="total"}' not in text


# -------------------------------------------------------------- /debug/memory


def test_memory_payload_503_on_audit_violation():
    led = memledger.get_ledger()
    led.register("x", 10, "kvpool")
    led.register("x", 10, "kvpool")  # seed the anomaly
    status, body = memory_payload()
    assert status == 503
    assert body["status"] == "violated"
    assert body["audit"]["errors"]


def test_memory_payload_ok_shape(monkeypatch):
    monkeypatch.setattr(memledger, "live_device_bytes", lambda: 2000)
    memledger.get_ledger().register("program:als", 800, "program",
                                    owner="eng")
    status, body = memory_payload()
    assert status == 200 and body["status"] == "ok"
    assert body["registered_bytes"] == 800
    assert body["components"] == {"program": 800}
    assert body["entries"][0]["owner"] == "eng"
    assert body["unattributed_bytes"] == 1200
    assert isinstance(body["planner_ratios"], list)
    assert body["leak_alerts"] == []


# ------------------------------------------------------------- leak detector


def test_leak_detector_inert_without_live_baseline():
    clock = [0.0]
    det = LeakDetector(windows=2, min_bytes=1024,
                       clock=lambda: clock[0])
    det.note_free("kvpool", 1 << 20)  # no observe yet: CPU shape
    assert det.pending_count() == 0
    assert det.observe(10_000_000) == []


def test_leak_detector_min_bytes_filter():
    det = LeakDetector(windows=2, min_bytes=4096, clock=lambda: 0.0)
    det.observe(10_000_000)
    det.note_free("kvpool", 4095)
    assert det.pending_count() == 0
    det.note_free("kvpool", 4096)
    assert det.pending_count() == 1


def test_leak_detector_resolves_on_live_drop():
    det = LeakDetector(windows=2, min_bytes=1024, clock=lambda: 0.0)
    det.observe(10_000_000)
    det.note_free("kvpool", 4096)
    # live dropped by at least half the freed size: watch resolved
    assert det.observe(10_000_000 - 2048) == []
    assert det.pending_count() == 0
    assert det.alerts == []


def test_leak_detector_alerts_after_windows(default_log):
    hooks = []
    det = LeakDetector(windows=2, min_bytes=1024, clock=lambda: 7.0)
    det.add_hook(hooks.append)
    det.add_hook(hooks.append)  # idempotent
    det.observe(10_000_000)
    det.note_free("kvpool", 4096)
    assert det.observe(10_000_000) == []  # window 1 of 2
    fired = det.observe(10_000_000)       # window 2: verdict
    assert len(fired) == 1
    alert = fired[0]
    assert alert["component"] == "kvpool"
    assert alert["freed_bytes"] == 4096
    assert alert["windows"] == 2
    assert alert["t"] == 7.0
    assert hooks == [alert]
    assert det.alerts == [alert]
    assert det.pending_count() == 0
    default_log.close()
    events, _ = load_events(default_log.path)
    leaks = [r for r in events
             if r.get("kind") == "mem" and r.get("ev") == "leak"]
    assert len(leaks) == 1 and leaks[0]["component"] == "kvpool"


def test_global_detector_wired_to_ledger_frees(monkeypatch):
    det = memledger.get_leak_detector()
    det.observe(100 << 20)  # give the detector a live baseline
    led = memledger.get_ledger()
    led.register("kvpool:big", 64 << 20, "kvpool")
    led.free("kvpool:big")
    assert det.pending_count() == 1


# -------------------------------------------------------------- OOM forensics


def test_is_oom_error_classifier():
    assert is_oom_error(RuntimeError("RESOURCE_EXHAUSTED: ..."))
    assert is_oom_error(RuntimeError("Out of memory allocating 1GB"))
    assert is_oom_error(RuntimeError("backend OOM"))
    assert not is_oom_error(ValueError("bad bucket"))

    class PagePoolExhausted(Exception):
        pass

    assert is_oom_error(PagePoolExhausted("pool dry"))


def test_oom_dump_writes_parseable_artifact(tmp_path, default_log):
    led = memledger.get_ledger()
    led.register("kvpool:eng", 1 << 20, "kvpool", owner="eng")
    led.register("program:als", 1 << 18, "program", owner="eng")
    with config_context(obs_profile_dir=str(tmp_path)):
        path = dump_oom_forensics("PagePoolExhausted: pool dry",
                                  extra={"bucket": "16x8"})
        assert path and os.path.exists(path)
        # rate limited: a second dump inside the window is skipped
        assert dump_oom_forensics("again") is None
    lines = [json.loads(ln) for ln in
             open(path).read().splitlines() if ln]
    head = lines[0]
    assert head["kind"] == "mem" and head["ev"] == "oom"
    assert head["reason"].startswith("PagePoolExhausted")
    assert head["bucket"] == "16x8"
    assert head["audit"]["ok"]
    assert "components" not in head["reconcile"]  # entries carry the detail
    entries = [r for r in lines if r.get("ev") == "entry"]
    assert {e["name"] for e in entries} == {"kvpool:eng", "program:als"}
    default_log.close()
    events, _ = load_events(default_log.path)
    dumps = [r for r in events
             if r.get("kind") == "mem" and r.get("ev") == "oom_dump"]
    assert len(dumps) == 1 and dumps[0]["path"] == path


def test_oom_dump_prunes_to_newest_16(tmp_path):
    with config_context(obs_profile_dir=str(tmp_path)):
        for i in range(20):
            assert dump_oom_forensics(f"oom {i}", min_interval_s=0.0)
    mine = [n for n in os.listdir(tmp_path)
            if n.startswith("marlin_oom_") and n.endswith(".jsonl")]
    assert len(mine) <= 16


# ------------------------------------------------ measured-peak calibration


def test_admission_ratio_planner_zero_is_uncalibrated():
    assert admission_ratio(0, ("lm_decode_paged",), "k0") == 1.0
    assert admission_ratio(-5, ("lm_decode_paged",), "k0b") == 1.0


def test_admission_ratio_prefers_live_measurement(monkeypatch):
    monkeypatch.setattr(memledger, "measured_peak_bytes",
                        lambda programs, key: 5000)
    assert admission_ratio(1000, ("p",), "k1") == 5.0


def test_admission_ratio_clamps_to_floor_and_cap(monkeypatch):
    # calibration only ever tightens admission (floor 1.0), and a corrupt
    # table must not brick it entirely (cap 32.0)
    monkeypatch.setattr(memledger, "measured_peak_bytes",
                        lambda programs, key: 500)
    assert admission_ratio(1000, ("p",), "k2") == 1.0
    monkeypatch.setattr(memledger, "measured_peak_bytes",
                        lambda programs, key: 100_000)
    assert admission_ratio(1000, ("p",), "k3") == 32.0


def test_admission_ratio_caches_per_key(monkeypatch):
    calls = []

    def fake_peak(programs, key):
        calls.append(key)
        return 3000

    monkeypatch.setattr(memledger, "measured_peak_bytes", fake_peak)
    assert admission_ratio(1000, ("p",), "k4") == 3.0
    assert admission_ratio(1000, ("p",), "k4") == 3.0
    assert calls == ["k4"]  # second hit came from the cache
    memledger.reset_ledger()  # the test hook clears the cache too
    assert admission_ratio(1000, ("p",), "k4") == 3.0
    assert calls == ["k4", "k4"]


def test_admission_ratio_falls_back_to_aot_table(monkeypatch):
    from marlin_tpu.models import planner

    monkeypatch.setattr(memledger, "measured_peak_bytes",
                        lambda programs, key: None)
    monkeypatch.setattr(planner, "bucket_calibration",
                        lambda key: 4500)
    assert admission_ratio(1000, ("p",), "k5") == 4.5
    monkeypatch.setattr(planner, "bucket_calibration", lambda key: None)
    assert admission_ratio(1000, ("p",), "k6") == 1.0


def test_ratio_table_reads_the_aot_report(monkeypatch):
    from marlin_tpu.models import planner

    rows = ratio_table()
    # the committed AOT_MEMORY.json carries the calibrated serve buckets
    assert rows, "AOT_MEMORY.json serve_buckets missing or empty"
    for r in rows:
        assert set(r) == {"bucket", "planner_bytes",
                          "measured_peak_bytes", "planner_ratio",
                          "calibration"}
        assert r["calibration"] >= 1.0
    monkeypatch.setattr(planner, "_AOT_MEMORY", "/nonexistent/x.json")
    assert ratio_table() == []


def test_engine_calibration_neutral_on_toy_cpu_model(params, monkeypatch):
    # a toy CPU model's program key is never in the AOT table and CPU
    # ProgramCosts carry no memory analysis -> ratio exactly 1.0, the
    # admission charge bit-identical to pre-ledger behavior; a measured
    # ratio scales the charge
    from marlin_tpu.serving import Request, ServeEngine

    with ServeEngine(params, HEADS, buckets=BUCKETS, max_batch=4,
                     max_wait_ms=0.0, queue_depth=64, page_len=PAGE_LEN,
                     num_pages=256) as eng:
        req = Request(prompt=[1, 2, 3], steps=2)
        bucket = BUCKETS[0]
        assert eng._calibrate_cost(req, bucket, 10) == 10
        eng._calib_ratios.clear()
        monkeypatch.setattr(memledger, "admission_ratio",
                            lambda planner, programs, key: 4.0)
        assert eng._calibrate_cost(req, bucket, 10) == 40
        assert eng._calibrate_cost(req, bucket, 7) == 28  # cached ratio


# ----------------------------------------------------- snapshots and reports


def test_emit_snapshot_to_explicit_log(tmp_path):
    led = memledger.get_ledger()
    led.register("kvpool:x", 2048, "kvpool")
    log = EventLog(str(tmp_path / "snap.jsonl"))
    emit_snapshot(log=log)
    log.close()
    events, _ = load_events(str(tmp_path / "snap.jsonl"))
    assert len(events) == 1
    rec = events[0]
    assert rec["kind"] == "mem" and rec["ev"] == "snapshot"
    assert rec["components"] == {"kvpool": 2048}
    assert rec["total_bytes"] == 2048


def test_report_memory_section_renders_from_mem_records():
    events = [
        {"kind": "serve", "ev": "x", "t": 1.0},
        {"kind": "mem", "ev": "snapshot", "t": 2.0,
         "components": {"kvpool": 800, "program": 200},
         "total_bytes": 1000},
        {"kind": "mem", "ev": "leak", "t": 3.0, "component": "kvpool",
         "freed_bytes": 4096, "live_drop_bytes": 0, "windows": 3},
        {"kind": "mem", "ev": "oom_dump", "t": 4.0, "reason": "oom",
         "path": "/tmp/marlin_oom_1_0.jsonl"},
    ]
    out = _memory_attribution_section(events)
    text = "\n".join(out)
    assert out[0] == "== memory attribution =="
    assert "last attribution (1000 bytes registered)" in text
    assert "kvpool" in text and "80.0%" in text
    assert "leak alerts: 1" in text
    assert "OOM forensics dumps: 1" in text
    # pre-ledger logs carry no mem records: the section must vanish so
    # old goldens stay byte-identical
    assert _memory_attribution_section([{"kind": "serve", "t": 1.0}]) == []


def test_console_memory_panel(monkeypatch):
    monkeypatch.setattr(memledger, "live_device_bytes", lambda: None)
    led = memledger.get_ledger()
    led.register("kvpool:x", 700, "kvpool")
    led.register("program:y", 300, "program")
    _, body = memory_payload()
    body["leak_alerts"] = [{"component": "kvpool", "freed_bytes": 4096,
                            "live_drop_bytes": 0, "windows": 3, "t": 0.0}]
    frame = console_render({}, {"scopes": []}, memory=body)
    assert "memory: registered=1000 live=n/a unattributed=n/a" in frame
    assert "kvpool" in frame and "program" in frame
    assert "LEAK kvpool: freed 4096 B" in frame
    assert "LEDGER AUDIT VIOLATED" not in frame
    # the violated frame is the one an operator most needs to see
    led.register("kvpool:x", 700, "kvpool")
    _, body = memory_payload()
    frame = console_render({}, {"scopes": []}, memory=body)
    assert "LEDGER AUDIT VIOLATED" in frame
    # a memory-less server renders the pre-ledger layout
    frame = console_render({}, {"scopes": []})
    assert "memory:" not in frame
