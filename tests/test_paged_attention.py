"""Fused paged decode-attention kernel suite (interpret mode on CPU).

Three layers: kernel-vs-numpy numerics (GQA head grouping, ragged per-row
lengths, page-boundary lengths, dummy/mid-prefill rows), the greedy
bit-identity grid across decode backends (``kernel='pallas'`` vs the
``'gather'`` reference vs unpaged :func:`lm_generate` — the serving
contract: swapping the attention kernel must not change a single emitted
token), and the engine end to end with ``decode_kernel='pallas'``
(including the ``serve_page_len`` alignment the backend forces). The
interpret path runs the REAL kernel body — same index_map, same
online-softmax accumulation — so these tests gate the Mosaic kernel's
logic, not a shadow implementation.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from marlin_tpu.models import TransformerLM
from marlin_tpu.models.transformer import (init_kv_pages, lm_decode_paged,
                                           lm_generate, lm_prefill_paged,
                                           resolve_decode_kernel)
from marlin_tpu.ops.paged_attention import (PAGE_SUBLANE, align_page_len,
                                            paged_attention_cost,
                                            paged_decode_attention)
from marlin_tpu.serving import STATUS_OK, Request, ServeEngine

PAGE_LEN = 8  # kernel-legal (multiple of PAGE_SUBLANE); tests/test_paging.py
#               keeps exercising the 4-entry geometry on the gather path


# ------------------------------------------------------- numpy reference


def _ref_attention(q, k_pages, v_pages, tables, lengths):
    """Straight-line numpy decode attention: gather each row's context by
    block table, mask past its length, softmax, weigh V. The obvious
    formulation the kernel must reproduce."""
    q = np.asarray(q, np.float32)
    kp = np.asarray(k_pages, np.float32)
    vp = np.asarray(v_pages, np.float32)
    B, kvh, group, dh = q.shape
    W = tables.shape[1]
    page_len = kp.shape[1]
    out = np.zeros_like(q)
    for b in range(B):
        k = kp[tables[b]].reshape(W * page_len, kvh, dh)
        v = vp[tables[b]].reshape(W * page_len, kvh, dh)
        n = int(np.clip(lengths[b], 1, W * page_len))
        s = np.einsum("kgd,tkd->kgt", q[b], k[:n]) / np.sqrt(dh)
        s = s - s.max(axis=2, keepdims=True)
        p = np.exp(s)
        p = p / p.sum(axis=2, keepdims=True)
        out[b] = np.einsum("kgt,tkd->kgd", p, v[:n])
    return out


def _random_case(rng, B=4, kvh=2, group=2, dh=8, W=3, num_pages=16,
                 dtype=np.float32):
    q = rng.standard_normal((B, kvh, group, dh)).astype(dtype)
    kp = rng.standard_normal((num_pages, PAGE_LEN, kvh, dh)).astype(dtype)
    vp = rng.standard_normal((num_pages, PAGE_LEN, kvh, dh)).astype(dtype)
    # distinct live pages per row (page 0 is the pool's dummy)
    tables = (1 + rng.permutation(num_pages - 1)[:B * W]).reshape(B, W)
    tables = tables.astype(np.int32)
    return q, kp, vp, tables


def test_kernel_matches_reference_gqa_ragged():
    """GQA (kv_heads < heads) with ragged lengths straddling page
    boundaries: the in-place kernel matches the gathered reference."""
    rng = np.random.default_rng(0)
    q, kp, vp, tables = _random_case(rng)
    lengths = np.array([1, 9, 17, 24], np.int32)  # mid-page, full-table
    got = paged_decode_attention(q, kp, vp, tables, lengths, interpret=True)
    want = _ref_attention(q, kp, vp, tables, lengths)
    np.testing.assert_allclose(np.asarray(got), want, atol=2e-6, rtol=2e-6)


def test_kernel_page_boundary_lengths():
    """Lengths landing exactly on page edges — the off-by-one hotspot for
    the absolute-position mask ``w*page_len + t < length``."""
    rng = np.random.default_rng(1)
    q, kp, vp, tables = _random_case(rng)
    for n in (PAGE_LEN - 1, PAGE_LEN, PAGE_LEN + 1, 2 * PAGE_LEN,
              3 * PAGE_LEN):
        lengths = np.full(4, n, np.int32)
        got = paged_decode_attention(q, kp, vp, tables, lengths,
                                     interpret=True)
        want = _ref_attention(q, kp, vp, tables, lengths)
        np.testing.assert_allclose(np.asarray(got), want, atol=2e-6,
                                   rtol=2e-6)


def test_kernel_dummy_rows_are_harmless():
    """Rows still prefilling ride the batch with an all-dummy (zero) table
    and length 1 — the dense-slab dummy-row contract. Their outputs must be
    finite (the scheduler discards them) and must not perturb live rows."""
    rng = np.random.default_rng(2)
    q, kp, vp, tables = _random_case(rng)
    lengths = np.array([12, 1, 20, 1], np.int32)
    tables = tables.copy()
    tables[1] = 0  # mid-prefill rows point at the dummy page
    tables[3] = 0
    got = np.asarray(paged_decode_attention(q, kp, vp, tables, lengths,
                                            interpret=True))
    assert np.isfinite(got).all()
    want = _ref_attention(q, kp, vp, tables, lengths)
    np.testing.assert_allclose(got[[0, 2]], want[[0, 2]], atol=2e-6,
                               rtol=2e-6)


def test_kernel_bf16_matches_f32_reference():
    """bf16 q/slab run the same masked online softmax; scores and the
    accumulator stay f32, so the error is operand rounding, not drift."""
    rng = np.random.default_rng(3)
    q, kp, vp, tables = _random_case(rng)
    lengths = np.array([5, 11, 24, 16], np.int32)
    got = paged_decode_attention(
        jnp.asarray(q, jnp.bfloat16), jnp.asarray(kp, jnp.bfloat16),
        jnp.asarray(vp, jnp.bfloat16), tables, lengths, interpret=True)
    assert got.dtype == jnp.bfloat16
    want = _ref_attention(q, kp, vp, tables, lengths)
    np.testing.assert_allclose(np.asarray(got, np.float32), want, atol=0.05,
                               rtol=0.05)


def test_kernel_length_clamping():
    """Out-of-range lengths clamp to [1, W*page_len] — a row can never
    attend past its table extent nor to zero positions."""
    rng = np.random.default_rng(4)
    q, kp, vp, tables = _random_case(rng)
    wild = np.array([0, -3, 999, 24], np.int32)
    clamped = np.array([1, 1, 24, 24], np.int32)
    got = paged_decode_attention(q, kp, vp, tables, wild, interpret=True)
    want = _ref_attention(q, kp, vp, tables, clamped)
    np.testing.assert_allclose(np.asarray(got), want, atol=2e-6, rtol=2e-6)


def test_page_len_validation_and_alignment():
    rng = np.random.default_rng(5)
    q = rng.standard_normal((1, 2, 1, 8)).astype(np.float32)
    bad = rng.standard_normal((4, 4, 2, 8)).astype(np.float32)  # page_len 4
    with pytest.raises(ValueError, match="multiple of"):
        paged_decode_attention(q, bad, bad, np.zeros((1, 2), np.int32),
                               np.ones(1, np.int32), interpret=True)
    assert align_page_len(1) == PAGE_SUBLANE
    assert align_page_len(8) == 8
    assert align_page_len(9) == 16
    with pytest.raises(ValueError):
        align_page_len(0)


def test_cost_model_shape():
    """The analytic cost dict feeds ProgramCosts.capture on the Mosaic
    path: cost_analysis()-shaped keys, flops/bytes scale with the table."""
    c1 = paged_attention_cost(4, 3, 8, 2, 2, 16)
    c2 = paged_attention_cost(4, 6, 8, 2, 2, 16)
    assert set(c1) == {"flops", "bytes accessed"}
    assert c2["flops"] == 2 * c1["flops"]
    assert c1["flops"] > 0 and c1["bytes accessed"] > 0


# ------------------------------------------- backend bit-identity grid


HEADS = 4
KV_HEADS = 2  # GQA: 2 query heads share each K/V head


@pytest.fixture(scope="module")
def params():
    return TransformerLM(vocab=32, d_model=16, heads=HEADS, layers=2,
                         kv_heads=KV_HEADS, seed=11).init_params()


def _ref(params, prompt, steps):
    prompt = np.asarray(prompt, np.int32)
    return np.asarray(lm_generate(
        params, prompt, jax.random.key(0), heads=HEADS,
        max_len=len(prompt) + steps, steps=steps)).tolist()


def _prefill_row(params, pages, table, prompt):
    """One-shot page-aligned prefill of ``prompt`` into ``table``'s pages
    (chunk padded with zeros past the prompt, as the prefill contract
    requires); returns ``(pages, first_token)``."""
    n = len(prompt)
    C = -(-n // PAGE_LEN) * PAGE_LEN
    chunk = np.zeros(C, np.int32)
    chunk[:n] = prompt
    tbl = np.concatenate([np.asarray(table, np.int32), np.zeros(2, np.int32)])
    pages, f = lm_prefill_paged(params, pages, tbl, chunk, 0, n, heads=HEADS,
                                page_len=PAGE_LEN)
    return pages, int(f)


def _decode_stream(params, kernel, prompts, steps, num_pages=32):
    """Prefill each prompt into its own pages, then run ``steps`` greedy
    decode steps through ``lm_decode_paged`` with the chosen backend; rows
    whose prompt is shorter keep decoding (ragged positions in one batch).
    Returns the per-row token streams (first token + decodes)."""
    B = len(prompts)
    W = max((len(p) + steps + PAGE_LEN - 1) // PAGE_LEN + 1 for p in prompts)
    pages = init_kv_pages(params, num_pages, PAGE_LEN, HEADS)
    tables = np.zeros((B, W), np.int32)
    first = np.zeros(B, np.int32)
    nxt_page = 1
    for b, prompt in enumerate(prompts):
        need = (len(prompt) + steps + PAGE_LEN - 1) // PAGE_LEN
        tables[b, :need] = range(nxt_page, nxt_page + need)
        nxt_page += need
        pages, first[b] = _prefill_row(params, pages, tables[b], prompt)
    streams = [[int(first[b])] for b in range(B)]
    positions = np.array([len(p) for p in prompts], np.int32)
    cur = first.copy()
    done = np.ones(B, np.int32)
    z = np.zeros(B, np.int32)
    for _ in range(steps - 1):
        pages, nxt = lm_decode_paged(
            params, pages, tables, positions, cur, done,
            z.astype(np.uint32), z.astype(np.float32),
            np.ones(B, np.float32), z, heads=HEADS, page_len=PAGE_LEN,
            kernel=kernel)
        nxt = np.asarray(nxt)
        for b in range(B):
            streams[b].append(int(nxt[b]))
        positions += 1
        done += 1
        cur = nxt.astype(np.int32)
    return streams


def test_greedy_bit_identity_pallas_vs_gather_vs_unpaged(params):
    """The contract: identical greedy token streams from the pallas kernel,
    the gather reference, and unpaged lm_generate — GQA model, ragged
    prompts (rows cross page boundaries on different steps)."""
    prompts = [np.arange(5) % 32, np.arange(9) % 32, np.arange(12) % 32,
               np.arange(7)[::-1] % 32]
    steps = 8
    gather = _decode_stream(params, "gather", prompts, steps)
    pallas = _decode_stream(params, "pallas", prompts, steps)
    assert pallas == gather
    for b, prompt in enumerate(prompts):
        assert gather[b] == _ref(params, prompt, steps)[len(prompt):]


def test_bit_identity_with_dummy_table_rows(params):
    """A mid-prefill row (all-zero table, the scheduler's dummy contract)
    riding the batch must not perturb live rows' streams, under either
    backend."""
    prompts = [np.arange(6) % 32, np.arange(10) % 32]
    steps = 6
    for kernel in ("gather", "pallas"):
        solo = _decode_stream(params, kernel, prompts, steps)
        B = 3  # same rows + one dummy slot
        W = (10 + steps + PAGE_LEN - 1) // PAGE_LEN + 1
        pages = init_kv_pages(params, 32, PAGE_LEN, HEADS)
        tables = np.zeros((B, W), np.int32)
        first = np.zeros(B, np.int32)
        nxt_page = 1
        for b, prompt in enumerate(prompts):
            need = (len(prompt) + steps + PAGE_LEN - 1) // PAGE_LEN
            tables[b, :need] = range(nxt_page, nxt_page + need)
            nxt_page += need
            pages, first[b] = _prefill_row(params, pages, tables[b], prompt)
        streams = [[int(first[b])] for b in range(2)]
        positions = np.array([6, 10, 0], np.int32)  # row 2: dummy
        cur = first.copy()
        done = np.ones(B, np.int32)
        z = np.zeros(B, np.int32)
        for _ in range(steps - 1):
            pages, nxt = lm_decode_paged(
                params, pages, tables, positions, cur, done,
                z.astype(np.uint32), z.astype(np.float32),
                np.ones(B, np.float32), z, heads=HEADS, page_len=PAGE_LEN,
                kernel=kernel)
            nxt = np.asarray(nxt)
            for b in range(2):
                streams[b].append(int(nxt[b]))
            positions += 1
            done += 1
            cur = nxt.astype(np.int32)
        assert streams == solo


def test_resolve_decode_kernel():
    assert resolve_decode_kernel("gather") == "gather"
    assert resolve_decode_kernel("pallas") == "pallas"
    expected = "pallas" if jax.default_backend() == "tpu" else "gather"
    assert resolve_decode_kernel("auto") == expected
    assert resolve_decode_kernel(None) == expected  # config default: auto
    with pytest.raises(ValueError):
        resolve_decode_kernel("fused")


# ------------------------------------------------------- engine end to end


def test_engine_pallas_backend_end_to_end(params):
    """The engine with ``decode_kernel='pallas'``: serves correct greedy
    outputs and aligns its page geometry to the kernel's block shape."""
    eng = ServeEngine(params, HEADS, buckets=((16, 8),), max_batch=4,
                      queue_depth=16, page_len=6,  # NOT kernel-legal: aligns
                      num_pages=64, decode_kernel="pallas")
    try:
        assert eng._page_len == 8  # align_page_len(6)
        assert eng._decode_kernel == "pallas"
        prompt = (np.arange(9) % 32).astype(np.int32)
        res = eng.submit(Request(prompt=prompt, steps=5)).result(timeout=300)
        assert res.status == STATUS_OK
        # Result.tokens carries prompt + generated, as lm_generate returns
        assert res.tokens.tolist() == _ref(params, prompt, 5)
    finally:
        eng.close()


def test_engine_gather_backend_unchanged_geometry(params):
    """decode_kernel='gather' keeps the configured page_len verbatim — no
    silent geometry change for the reference path."""
    eng = ServeEngine(params, HEADS, buckets=((16, 4),), max_batch=2,
                      queue_depth=8, page_len=4, num_pages=64,
                      decode_kernel="gather")
    try:
        assert eng._page_len == 4
        assert eng._decode_kernel == "gather"
    finally:
        eng.close()
