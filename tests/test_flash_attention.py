"""Flash-attention Pallas kernel vs the dense oracle (interpret mode on CPU;
the same kernel compiles under Mosaic on TPU — exercised by the attn bench)."""

import numpy as np
import pytest

import jax.numpy as jnp

from marlin_tpu.ops.flash_attention import flash_attention_panel
from marlin_tpu.parallel.ring_attention import attention_reference


def _qkv(seq, d, seed):
    rng = np.random.default_rng(seed)
    return tuple(jnp.asarray(rng.standard_normal((seq, d)).astype(np.float32))
                 for _ in range(3))


def _init_state(sq, d):
    # m/l are 1-D lane-major rows (the (sq, 1) form tile-pads 128x in HBM)
    return (jnp.full((sq,), -1e30, jnp.float32),
            jnp.zeros((sq,), jnp.float32),
            jnp.zeros((sq, d), jnp.float32))


def _finish(m, l, acc):
    return np.asarray(acc / jnp.maximum(l, 1e-30)[:, None])


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("seq,d,valid", [(256, 128, 256), (512, 64, 400)])
def test_flash_panel_matches_oracle(causal, seq, d, valid):
    q, k, v = _qkv(seq, d, 0)
    m, l, acc = _init_state(seq, d)
    m, l, acc = flash_attention_panel(q, k, v, m, l, acc, 0, 0, valid,
                                      causal=causal, scale=d ** -0.5,
                                      bq=128, bkv=128)
    out = _finish(m, l, acc)
    ref = attention_reference(q[:valid], k[:valid], v[:valid], causal=causal)
    np.testing.assert_allclose(out[:valid], np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_two_panels_carry_state():
    # splitting K/V into two panels with carried (m, l, acc) — the ring
    # schedule — must equal one full pass
    seq, d = 256, 64
    q, k, v = _qkv(seq, d, 1)
    m, l, acc = _init_state(seq, d)
    half = seq // 2
    for p in range(2):
        kp, vp = k[p * half:(p + 1) * half], v[p * half:(p + 1) * half]
        m, l, acc = flash_attention_panel(q, kp, vp, m, l, acc, 0, p * half,
                                          seq, causal=True, scale=d ** -0.5,
                                          bq=128, bkv=128)
    out = _finish(m, l, acc)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(out, np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_flash_panel_rejects_indivisible_blocks():
    q, k, v = _qkv(96, 64, 2)
    m, l, acc = _init_state(96, 64)
    with pytest.raises(ValueError):
        flash_attention_panel(q, k, v, m, l, acc, 0, 0, 96,
                              causal=False, scale=0.125, bq=64, bkv=64)
