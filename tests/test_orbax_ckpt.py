"""Orbax checkpoint adapter: async save, retention, sharded + matrix-typed
restore (the production layer over io.checkpoint — SURVEY.md §5.4)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import marlin_tpu as mt
from marlin_tpu.io import OrbaxCheckpointer

pytest.importorskip("orbax.checkpoint")


def test_orbax_roundtrip_sharded(mesh, tmp_path):
    sh = NamedSharding(mesh, P("rows", None))
    w = jax.device_put(jnp.arange(64.0).reshape(8, 8), sh)
    state = {"w": w, "step_size": jnp.float32(0.5)}
    with OrbaxCheckpointer(str(tmp_path / "ck")) as ckpt:
        ckpt.save(state, 1)
        ckpt.wait()
        restored, step = ckpt.restore(state)
    assert step == 1
    np.testing.assert_array_equal(np.asarray(restored["w"]), np.asarray(w))
    assert restored["w"].sharding.is_equivalent_to(sh, 2)


def test_orbax_retention_and_latest(mesh, tmp_path):
    state = {"w": jnp.ones((4, 4))}
    with OrbaxCheckpointer(str(tmp_path / "ck"), max_to_keep=2) as ckpt:
        for s in (1, 2, 3):
            ckpt.save({"w": jnp.full((4, 4), float(s))}, s)
        ckpt.wait()
        assert ckpt.all_steps() == [2, 3]
        restored, step = ckpt.restore(state)
    assert step == 3
    assert float(restored["w"][0, 0]) == 3.0


def test_orbax_matrix_state(mesh, tmp_path):
    # matrices are pytrees: checkpoint a state holding one directly
    a = mt.DenseVecMatrix.random(0, 20, 12, mesh=mesh)
    state = {"factors": a, "step_size": jnp.float32(0.1)}
    with OrbaxCheckpointer(str(tmp_path / "ck")) as ckpt:
        ckpt.save(state, 7)
        ckpt.wait()
        restored, step = ckpt.restore(state)
    assert isinstance(restored["factors"], mt.DenseVecMatrix)
    np.testing.assert_array_equal(restored["factors"].to_numpy(), a.to_numpy())


def test_orbax_missing_raises(tmp_path):
    with OrbaxCheckpointer(str(tmp_path / "empty")) as ckpt:
        with pytest.raises(FileNotFoundError):
            ckpt.restore({"w": jnp.ones(3)})
