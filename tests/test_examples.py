"""Smoke tests for the example CLIs (the reference's examples are its manual
integration suite — SURVEY.md §4; here they run in-process on the CPU mesh)
and the C++ genmat tool."""

import shutil
import subprocess
import sys

import numpy as np
import pytest

import marlin_tpu as mt

import jax as _jax_mod

# jax-0.4.37-era gate: these cases exercise behaviour that only works in
# the top-level jax.shard_map / jax.typeof era (partial-auto shard_map,
# scan-carry replication checks) -- same class as tests/test_aot_tpu.py.
needs_modern_jax = pytest.mark.skipif(
    getattr(_jax_mod, "shard_map", None) is None
    or not hasattr(_jax_mod, "typeof"),
    reason="needs modern jax (top-level shard_map / typeof era)")



def test_matrix_multiply_cli(capsys):
    from examples.matrix_multiply import main

    main(["64", "48", "32", "8"])
    out = capsys.readouterr().out
    assert "used time" in out and "GFLOP/s" in out


def test_matrix_multiply_cli_files(tmp_path, capsys, mesh):
    a = np.random.default_rng(0).random((12, 12)).astype(np.float32)
    b = np.random.default_rng(1).random((12, 12)).astype(np.float32)
    pa, pb = str(tmp_path / "a.txt"), str(tmp_path / "b.txt")
    mt.DenseVecMatrix.from_array(a, mesh).save_to_file_system(pa)
    mt.DenseVecMatrix.from_array(b, mesh).save_to_file_system(pb)
    from examples.matrix_multiply import main

    c = main(["--files", pa, pb])
    np.testing.assert_allclose(c.to_numpy(), a @ b, rtol=1e-4, atol=1e-4)


def test_blas1_cli(capsys):
    from examples.blas1 import main

    for mode in ("local", "dist"):
        main([mode, "100", "4"])
    assert "inner product" in capsys.readouterr().out


def test_blas3_cli(capsys):
    from examples.blas3 import main

    for mode_args in (["32", "32", "32", "1"], ["32", "32", "32", "2"],
                      ["32", "32", "32", "3", "2", "2", "2"]):
        main(mode_args)
    out = capsys.readouterr().out
    assert "local multiply" in out and "broadcast multiply" in out and "rmm multiply" in out


def test_rmm_compare_tuned_mode(capsys):
    from examples.rmm_compare import main

    timings = main(["48", "32", "24", "tuned"])
    out = capsys.readouterr().out
    assert "fastest:" in out and len(timings) >= 2


def test_rmm_compare_cli(capsys):
    from examples.rmm_compare import main

    timings = main(["48", "48", "48", "all"])
    assert set(timings) == {"rmm", "gspmd", "broadcast"}
    assert "fastest:" in capsys.readouterr().out


def test_sparse_multiply_cli(capsys):
    from examples.sparse_multiply import main

    for mode in "1234567":
        main(["32", "32", "32", "0.1", mode])
    out = capsys.readouterr().out
    assert "millis" in out


def test_lu_example_cli(tmp_path, capsys, mesh):
    n = 12
    a = np.random.default_rng(0).random((n, n)).astype(np.float32) + n * np.eye(n, dtype=np.float32)
    p = str(tmp_path / "a.txt")
    mt.DenseVecMatrix.from_array(a, mesh).save_to_file_system(p)
    from examples.lu_decompose import main

    main([p, str(n), str(n), str(tmp_path / "out")])
    out = capsys.readouterr().out
    assert "LU used" in out
    l = mt.load_matrix_file(str(tmp_path / "out.L"), mesh).to_numpy()
    u = mt.load_matrix_file(str(tmp_path / "out.U"), mesh).to_numpy()
    perm = [int(x) for x in open(str(tmp_path / "out.perm")).read().split(",")]
    np.testing.assert_allclose(a[perm], l @ u, rtol=1e-3, atol=1e-3)


def test_lr_cli(capsys):
    from examples.logistic_regression import main

    main(["50", "10.0", "500", "10"])
    out = capsys.readouterr().out
    assert "accuracy" in out


def test_pagerank_cli(tmp_path, capsys):
    p = tmp_path / "edges.txt"
    p.write_text("0 1\n1 2\n2 0\n2 1\n")
    from examples.pagerank import main

    main([str(p), "30"])
    out = capsys.readouterr().out
    assert "node" in out


def test_als_cli(tmp_path, capsys):
    rng = np.random.default_rng(0)
    lines = [f"{u} {i} {rng.random() * 5:.3f}" for u in range(20) for i in range(10)
             if rng.random() < 0.5]
    p = tmp_path / "ratings.txt"
    p.write_text("\n".join(lines))
    from examples.als import main

    main([str(p), "3", "5", "0.1"])
    out = capsys.readouterr().out
    assert "RMSE" in out


def test_nn_cli(capsys):
    from examples.neural_network import main

    main(["synthetic", "-", "30", "16", "1.0", "128"])
    out = capsys.readouterr().out
    assert "train accuracy" in out


@needs_modern_jax
def test_long_context_training_cli(capsys):
    from examples.long_context_training import main

    losses = main(["128", "6", "32", "4", "1"])
    out = capsys.readouterr().out
    assert "tok/s" in out and losses[-1] < losses[0]
    assert "greedy continuation" in out


@needs_modern_jax
def test_pipeline_training_cli(capsys):
    from examples.pipeline_training import main

    losses = main(["8", "65", "6", "32", "4", "2"])
    out = capsys.readouterr().out
    assert "stages" in out and "tok/s" in out
    assert losses[-1] < losses[0]


@needs_modern_jax
def test_moe_training_cli(capsys):
    from examples.moe_training import main

    losses = main(["192", "8", "4", "2", "32", "1"])
    out = capsys.readouterr().out
    assert "load-balance aux" in out and "tok/s" in out
    assert losses[-1] < losses[0]
    assert "greedy continuation" in out


@needs_modern_jax
def test_long_context_training_cli_chunked(capsys):
    from examples.long_context_training import main

    # remat + chunked head (the lct_long combination), chunk ∤ seq-1
    losses = main(["128", "6", "32", "4", "1", "ring", "1", "48"])
    out = capsys.readouterr().out
    assert "loss_chunk=48" in out and losses[-1] < losses[0]


@pytest.mark.parametrize("strategy", ["ring", "ulysses"])
def test_attention_cli(capsys, strategy):
    from examples.attention import main

    main(["64", "16", "1", "4", strategy])
    out = capsys.readouterr().out
    assert strategy in out and "GFLOP/s" in out


@pytest.mark.skipif(shutil.which("g++") is None, reason="no C++ toolchain")
def test_genmat_tool(tmp_path, mesh):
    import os

    build = subprocess.run(
        ["make", "-C", os.path.join(os.path.dirname(__file__), "..", "tools")],
        capture_output=True, text=True,
    )
    assert build.returncode == 0, build.stderr
    exe = os.path.join(os.path.dirname(__file__), "..", "tools", "genmat")
    out = subprocess.run([exe, "5", "4", "7"], capture_output=True, text=True)
    assert out.returncode == 0
    path = tmp_path / "gen.txt"
    path.write_text(out.stdout)
    m = mt.load_matrix_file(str(path), mesh)
    arr = m.to_numpy()
    assert arr.shape == (5, 4)
    assert (arr >= 0).all() and (arr < 5).all()
    # deterministic per seed
    again = subprocess.run([exe, "5", "4", "7"], capture_output=True, text=True)
    assert again.stdout == out.stdout
    other = subprocess.run([exe, "5", "4", "8"], capture_output=True, text=True)
    assert other.stdout != out.stdout


def test_distributed_training_cli(capsys, tmp_path):
    from examples.distributed_training import main

    main(["60", "16", "64", str(tmp_path / "ckpt")])
    out = capsys.readouterr().out
    assert "data-parallel" in out and "accuracy" in out


@needs_modern_jax
def test_decode_serving_cli(capsys):
    from examples.decode_serving import main

    outs = main(["3", "12", "4", "32", "2", "1"])
    assert len(outs) == 3
    out = capsys.readouterr().out
    assert "batched" in out and "one-at-a-time" in out
