"""Empirical multiply-strategy autotuner (programmatic RMMcompare)."""

import numpy as np
import pytest

import marlin_tpu as mt
from marlin_tpu.parallel import autotune


@pytest.fixture(autouse=True)
def _fresh_cache(tmp_path):
    # point the disk layer at a per-test path so clear_cache() (which clears
    # BOTH layers) never touches a developer's real ~/.cache file
    with mt.config_context(autotune_cache_path=str(tmp_path / "autotune.json")):
        autotune.clear_cache()
        yield
        autotune.clear_cache()


def test_tune_multiply_times_candidates(mesh):
    a = mt.DenseVecMatrix.random(0, 64, 48, mesh=mesh)
    b = mt.DenseVecMatrix.random(1, 48, 32, mesh=mesh)
    results = mt.tune_multiply(a, b, reps=1)
    assert len(results) >= 2
    assert all(sec > 0 for _, sec in results)
    # sorted fastest-first
    secs = [sec for _, sec in results]
    assert secs == sorted(secs)


def test_tuned_strategy_matches_oracle(mesh):
    a = mt.DenseVecMatrix.random(2, 40, 24, mesh=mesh)
    b = mt.DenseVecMatrix.random(3, 24, 16, mesh=mesh)
    c = a.multiply(b, strategy="tuned")
    np.testing.assert_allclose(c.to_numpy(), a.to_numpy() @ b.to_numpy(),
                               rtol=2e-4, atol=2e-4)


def test_tuned_uses_cache(mesh):
    a = mt.DenseVecMatrix.random(4, 32, 32, mesh=mesh)
    b = mt.DenseVecMatrix.random(5, 32, 32, mesh=mesh)
    a.multiply(b, strategy="tuned")
    assert len(autotune._CACHE) == 1
    calls = {"n": 0}
    orig = autotune.tune_multiply

    def spy(*args, **kw):
        calls["n"] += 1
        return orig(*args, **kw)

    autotune.tune_multiply = spy
    try:
        a.multiply(b, strategy="tuned")  # same config -> no re-tune
    finally:
        autotune.tune_multiply = orig
    assert calls["n"] == 0


def test_explicit_strategy_list_does_not_pin_cache(mesh):
    a = mt.DenseVecMatrix.random(6, 32, 32, mesh=mesh)
    b = mt.DenseVecMatrix.random(7, 32, 32, mesh=mesh)
    results = mt.tune_multiply(a, b, strategies=["gspmd", "broadcast"], reps=1)
    assert {s for s, _ in results} <= {"gspmd", "broadcast"}
    # a restricted benchmark must never pin strategy="tuned" dispatch
    assert len(autotune._CACHE) == 0


def test_no_viable_strategy_raises(mesh):
    a = mt.DenseVecMatrix.random(8, 16, 16, mesh=mesh)
    b = mt.DenseVecMatrix.random(9, 16, 16, mesh=mesh)
    with pytest.raises(ValueError):
        mt.tune_multiply(a, b, strategies=["not_a_strategy"])


def test_shape_mismatch_error_is_clear(mesh):
    a = mt.DenseVecMatrix.random(10, 64, 48, mesh=mesh)
    b = mt.DenseVecMatrix.random(11, 32, 32, mesh=mesh)
    with pytest.raises(ValueError, match="inner dim mismatch"):
        mt.tune_multiply(a, b)


def test_cache_keyed_on_layout(mesh):
    # same shapes, different matrix classes/layouts -> distinct cache entries
    a1 = mt.DenseVecMatrix.random(12, 32, 32, mesh=mesh)
    b1 = mt.DenseVecMatrix.random(13, 32, 32, mesh=mesh)
    a2 = mt.BlockMatrix.random(12, 32, 32, mesh=mesh)
    b2 = mt.BlockMatrix.random(13, 32, 32, mesh=mesh)
    a1.multiply(b1, strategy="tuned")
    a2.multiply(b2, strategy="tuned")
    assert len(autotune._CACHE) == 2


def test_vector_operand_rejected_clearly(mesh):
    a = mt.DenseVecMatrix.random(20, 32, 32, mesh=mesh)
    v = np.ones((32,), np.float32)
    with pytest.raises(ValueError, match="2-D right operand"):
        mt.tune_multiply(a, v)


def _seed_cache_entry(mesh, strategy="gspmd", seed=40):
    """A (key, operands) pair with the winner planted in both cache layers —
    persistence tests must not depend on real multiplies succeeding."""
    a = mt.DenseVecMatrix.random(seed, 32, 32, mesh=mesh)
    b = mt.DenseVecMatrix.random(seed + 1, 32, 32, mesh=mesh)
    key = autotune._cache_key(a, b, None)
    autotune._CACHE[key] = strategy
    autotune._persist(key, strategy)
    return key, a, b


def _simulate_restart():
    autotune._CACHE.clear()
    autotune._disk = None  # force a reload from the file


def test_disk_cache_survives_restart(mesh, monkeypatch):
    import os

    key, a, b = _seed_cache_entry(mesh)
    path = mt.get_config().autotune_cache_path
    assert os.path.exists(path)
    _simulate_restart()

    def boom(*args, **kw):
        raise AssertionError("re-tuned despite a persisted winner")

    monkeypatch.setattr(autotune, "tune_multiply", boom)
    assert autotune.best_strategy(a, b) == "gspmd"
    assert len(autotune._CACHE) == 1  # promoted back into the memory layer


def test_clear_cache_clears_both_layers(mesh):
    import os

    _seed_cache_entry(mesh)
    path = mt.get_config().autotune_cache_path
    assert os.path.exists(path)
    autotune.clear_cache()
    assert len(autotune._CACHE) == 0
    assert not os.path.exists(path)


def test_disk_layer_disabled_by_empty_path(mesh, tmp_path):
    import os

    with mt.config_context(autotune_cache_path=""):
        key, a, b = _seed_cache_entry(mesh, seed=44)
    assert not os.path.exists(os.path.join(str(tmp_path), "autotune.json"))


def test_corrupt_disk_file_degrades_to_retune(mesh, monkeypatch):
    key, a, b = _seed_cache_entry(mesh, seed=48)
    path = mt.get_config().autotune_cache_path
    with open(path, "w") as f:
        f.write("{ not json")
    _simulate_restart()
    tuned = {"n": 0}

    def fake_tune(mat, other, **kw):
        tuned["n"] += 1
        autotune._CACHE[autotune._cache_key(mat, other, None)] = "rmm"
        return [("rmm", 0.001)]

    monkeypatch.setattr(autotune, "tune_multiply", fake_tune)
    # corrupt file must not crash; it just loses the persisted winners
    assert autotune.best_strategy(a, b) == "rmm"
    assert tuned["n"] == 1


def test_stale_persisted_strategy_triggers_retune(mesh, monkeypatch):
    """A winner persisted by an older version whose engine was renamed must
    degrade to a retune, never poison every tuned multiply."""
    key, a, b = _seed_cache_entry(mesh, seed=56, strategy="engine_v0_name")
    _simulate_restart()
    tuned = {"n": 0}

    def fake_tune(mat, other, **kw):
        tuned["n"] += 1
        autotune._CACHE[autotune._cache_key(mat, other, None)] = "gspmd"
        return [("gspmd", 0.001)]

    monkeypatch.setattr(autotune, "tune_multiply", fake_tune)
    assert autotune.best_strategy(a, b) == "gspmd"
    assert tuned["n"] == 1  # the stale name was ignored


def test_persist_merges_with_concurrent_writes(mesh):
    """Merge-on-write: a winner another process wrote between our load and
    our persist survives in the file (no lost update)."""
    import json

    key1, a, b = _seed_cache_entry(mesh, seed=60)
    path = mt.get_config().autotune_cache_path
    # simulate another process adding a winner behind our back
    other = json.load(open(path))
    other["('OtherProc', (1, 1))"] = "rmm"
    json.dump(other, open(path, "w"))
    key2 = autotune._cache_key(a, b, "highest")
    autotune._persist(key2, "ring")
    merged = json.load(open(path))
    assert merged["('OtherProc', (1, 1))"] == "rmm"
    assert merged[repr(key1)] == "gspmd"
    assert merged[repr(key2)] == "ring"


def test_disk_key_distinguishes_precision(mesh):
    _, a, b = _seed_cache_entry(mesh, seed=52)
    _simulate_restart()
    key_high = autotune._cache_key(a, b, "highest")
    with autotune._DISK_LOCK:
        assert repr(key_high) not in autotune._disk_layer()


def test_unknown_candidate_skipped_not_fatal(mesh):
    """An unsupported candidate mixed into an explicit set is skipped via
    UnknownStrategyError (no message-text matching, ADVICE r3); the viable
    one still gets timed."""
    a = mt.DenseVecMatrix.random(30, 32, 32, mesh=mesh)
    b = mt.DenseVecMatrix.random(31, 32, 32, mesh=mesh)
    results = mt.tune_multiply(a, b, strategies=["gspmd", "not_a_strategy"])
    assert [s for s, _ in results] == ["gspmd"]


def test_cache_key_includes_device_kind(mesh):
    """Winners are hardware-specific: a v5e tiling loses on a v4. The key's
    tail must carry (platform, device_kind) so a cache file that moves
    between machines re-tunes instead of importing the wrong winner."""
    a = mt.DenseVecMatrix.random(70, 32, 32, mesh=mesh)
    b = mt.DenseVecMatrix.random(71, 32, 32, mesh=mesh)
    key = autotune._cache_key(a, b, None)
    assert key[-2:] == autotune._device_sig()


def test_unversioned_disk_file_ignored(mesh, monkeypatch):
    """A pre-versioning cache file (no __version__, or the wrong one) is
    keyed by the old device-blind scheme — the loader must drop it whole
    and let every configuration re-tune."""
    import json

    key, a, b = _seed_cache_entry(mesh, seed=64)
    path = mt.get_config().autotune_cache_path
    data = json.load(open(path))
    assert data["__version__"] == autotune._DISK_VERSION
    del data["__version__"]  # simulate a v1-era file
    json.dump(data, open(path, "w"))
    _simulate_restart()
    with autotune._DISK_LOCK:
        assert autotune._disk_layer() == {}
    tuned = {"n": 0}

    def fake_tune(mat, other, **kw):
        tuned["n"] += 1
        autotune._CACHE[autotune._cache_key(mat, other, None)] = "gspmd"
        return [("gspmd", 0.001)]

    monkeypatch.setattr(autotune, "tune_multiply", fake_tune)
    assert autotune.best_strategy(a, b) == "gspmd"
    assert tuned["n"] == 1


def test_version_key_survives_merge(mesh):
    """_persist's merge-on-write keeps the file loadable: the version key
    is re-stamped on every write, never dropped by the merge."""
    import json

    _seed_cache_entry(mesh, seed=68)
    key2 = ("Other", (2, 2))
    autotune._persist(key2, "rmm")
    data = json.load(open(mt.get_config().autotune_cache_path))
    assert data["__version__"] == autotune._DISK_VERSION
    assert data[repr(key2)] == "rmm"
