"""Empirical multiply-strategy autotuner (programmatic RMMcompare)."""

import numpy as np
import pytest

import marlin_tpu as mt
from marlin_tpu.parallel import autotune


@pytest.fixture(autouse=True)
def _fresh_cache():
    autotune.clear_cache()
    yield
    autotune.clear_cache()


def test_tune_multiply_times_candidates(mesh):
    a = mt.DenseVecMatrix.random(0, 64, 48, mesh=mesh)
    b = mt.DenseVecMatrix.random(1, 48, 32, mesh=mesh)
    results = mt.tune_multiply(a, b, reps=1)
    assert len(results) >= 2
    assert all(sec > 0 for _, sec in results)
    # sorted fastest-first
    secs = [sec for _, sec in results]
    assert secs == sorted(secs)


def test_tuned_strategy_matches_oracle(mesh):
    a = mt.DenseVecMatrix.random(2, 40, 24, mesh=mesh)
    b = mt.DenseVecMatrix.random(3, 24, 16, mesh=mesh)
    c = a.multiply(b, strategy="tuned")
    np.testing.assert_allclose(c.to_numpy(), a.to_numpy() @ b.to_numpy(),
                               rtol=2e-4, atol=2e-4)


def test_tuned_uses_cache(mesh):
    a = mt.DenseVecMatrix.random(4, 32, 32, mesh=mesh)
    b = mt.DenseVecMatrix.random(5, 32, 32, mesh=mesh)
    a.multiply(b, strategy="tuned")
    assert len(autotune._CACHE) == 1
    calls = {"n": 0}
    orig = autotune.tune_multiply

    def spy(*args, **kw):
        calls["n"] += 1
        return orig(*args, **kw)

    autotune.tune_multiply = spy
    try:
        a.multiply(b, strategy="tuned")  # same config -> no re-tune
    finally:
        autotune.tune_multiply = orig
    assert calls["n"] == 0


def test_explicit_strategy_list_does_not_pin_cache(mesh):
    a = mt.DenseVecMatrix.random(6, 32, 32, mesh=mesh)
    b = mt.DenseVecMatrix.random(7, 32, 32, mesh=mesh)
    results = mt.tune_multiply(a, b, strategies=["gspmd", "broadcast"], reps=1)
    assert {s for s, _ in results} <= {"gspmd", "broadcast"}
    # a restricted benchmark must never pin strategy="tuned" dispatch
    assert len(autotune._CACHE) == 0


def test_no_viable_strategy_raises(mesh):
    a = mt.DenseVecMatrix.random(8, 16, 16, mesh=mesh)
    b = mt.DenseVecMatrix.random(9, 16, 16, mesh=mesh)
    with pytest.raises(ValueError):
        mt.tune_multiply(a, b, strategies=["not_a_strategy"])


def test_shape_mismatch_error_is_clear(mesh):
    a = mt.DenseVecMatrix.random(10, 64, 48, mesh=mesh)
    b = mt.DenseVecMatrix.random(11, 32, 32, mesh=mesh)
    with pytest.raises(ValueError, match="inner dim mismatch"):
        mt.tune_multiply(a, b)


def test_cache_keyed_on_layout(mesh):
    # same shapes, different matrix classes/layouts -> distinct cache entries
    a1 = mt.DenseVecMatrix.random(12, 32, 32, mesh=mesh)
    b1 = mt.DenseVecMatrix.random(13, 32, 32, mesh=mesh)
    a2 = mt.BlockMatrix.random(12, 32, 32, mesh=mesh)
    b2 = mt.BlockMatrix.random(13, 32, 32, mesh=mesh)
    a1.multiply(b1, strategy="tuned")
    a2.multiply(b2, strategy="tuned")
    assert len(autotune._CACHE) == 2


def test_vector_operand_rejected_clearly(mesh):
    a = mt.DenseVecMatrix.random(20, 32, 32, mesh=mesh)
    v = np.ones((32,), np.float32)
    with pytest.raises(ValueError, match="2-D right operand"):
        mt.tune_multiply(a, v)


def test_unknown_candidate_skipped_not_fatal(mesh):
    """An unsupported candidate mixed into an explicit set is skipped via
    UnknownStrategyError (no message-text matching, ADVICE r3); the viable
    one still gets timed."""
    a = mt.DenseVecMatrix.random(30, 32, 32, mesh=mesh)
    b = mt.DenseVecMatrix.random(31, 32, 32, mesh=mesh)
    results = mt.tune_multiply(a, b, strategies=["gspmd", "not_a_strategy"])
    assert [s for s, _ in results] == ["gspmd"]
