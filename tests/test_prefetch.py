"""Async host→device prefetch pipeline (parallel/prefetch.py).

Covers the pipeline's contract (ordering, backpressure, exception
propagation, clean shutdown), its chaos hooks (prefetch.produce fault point),
and the property the whole design rests on: streamed results are bit-for-bit
identical with prefetch on and off.
"""

import struct
import threading
import time

import numpy as np
import pytest

import marlin_tpu as mt
from marlin_tpu.parallel.prefetch import ChunkPrefetcher
from marlin_tpu.utils import faults
from marlin_tpu.utils.profiling import StageTimes


def _chunks(n=8, rows=16, cols=4, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.standard_normal((rows, cols)).astype(np.float32)
            for _ in range(n)]


def _alive_workers():
    return [t for t in threading.enumerate()
            if t.name.startswith("marlin-prefetch") and t.is_alive()]


# ------------------------------------------------------------------ contract
def test_ordering_single_worker():
    cs = _chunks(10)
    got = list(ChunkPrefetcher(iter(cs), workers=1, depth=2))
    assert len(got) == len(cs)
    for g, c in zip(got, cs):
        np.testing.assert_array_equal(np.asarray(g), c)


def test_ordering_many_workers():
    """Out-of-order completion (4 workers racing) must still yield source
    order — the reorder buffer, not scheduling luck."""
    cs = _chunks(24)
    got = list(ChunkPrefetcher(iter(cs), workers=4, depth=6))
    assert len(got) == len(cs)
    for g, c in zip(got, cs):
        np.testing.assert_array_equal(np.asarray(g), c)


def test_transform_runs_on_producer():
    cs = _chunks(6)
    tids = set()

    def transform(c):
        tids.add(threading.get_ident())
        return c * 2.0

    got = list(ChunkPrefetcher(iter(cs), transform, workers=2))
    for g, c in zip(got, cs):
        np.testing.assert_array_equal(np.asarray(g), c * 2.0)
    assert threading.get_ident() not in tids  # off the consumer's thread


def test_backpressure_bounds_inflight_chunks():
    """With depth=d and an idle consumer, at most d chunks are ever read."""
    produced = []

    def source():
        for c in _chunks(20):
            produced.append(len(produced))
            yield c

    pf = ChunkPrefetcher(source(), depth=3, workers=2)
    try:
        deadline = time.monotonic() + 2.0
        while len(produced) < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
        time.sleep(0.3)  # give eager workers every chance to overshoot
        assert len(produced) == 3
        # consuming one admits exactly one more read
        next(pf)
        deadline = time.monotonic() + 2.0
        while len(produced) < 4 and time.monotonic() < deadline:
            time.sleep(0.01)
        time.sleep(0.2)
        assert len(produced) == 4
    finally:
        pf.close()


def test_hbm_budget_admission_is_stream_ordered():
    """Regression: with several workers racing a budget smaller than one
    chunk, chunk i+1's worker must not claim the budget ahead of chunk i's —
    the consumer needs i first, and i's worker would wait forever on a budget
    held by a chunk nobody can consume yet. Ordered admission makes this
    terminate; many chunks × many reps made the inversion near-certain under
    the old first-come admission."""
    for rep in range(5):
        cs = _chunks(40, rows=8, seed=rep)
        got = list(ChunkPrefetcher(iter(cs), depth=6, workers=4,
                                   hbm_budget_bytes=1))
        assert len(got) == len(cs)
        for g, c in zip(got, cs):
            np.testing.assert_array_equal(np.asarray(g), c)
    assert not _alive_workers()


def test_hbm_budget_lets_single_chunk_through():
    """A budget smaller than one chunk must not deadlock: a lone chunk always
    proceeds, the stream just serializes."""
    cs = _chunks(5)
    got = list(ChunkPrefetcher(iter(cs), depth=3, workers=2,
                               hbm_budget_bytes=1))
    assert len(got) == len(cs)
    for g, c in zip(got, cs):
        np.testing.assert_array_equal(np.asarray(g), c)


def test_source_exception_propagates_in_position():
    boom = ValueError("disk on fire")

    def source():
        for i, c in enumerate(_chunks(10)):
            if i == 3:
                raise boom
            yield c

    pf = ChunkPrefetcher(source(), workers=2, depth=4)
    got = [next(pf), next(pf), next(pf)]  # 0..2 still delivered
    assert len(got) == 3
    with pytest.raises(ValueError, match="disk on fire"):
        next(pf)
    assert not _alive_workers()  # error path joins the workers too


def test_transform_exception_propagates():
    def transform(c):
        if float(c[0, 0]) == 2.0:
            raise RuntimeError("bad chunk")
        return c

    cs = [np.full((4, 4), float(i), np.float32) for i in range(5)]
    pf = ChunkPrefetcher(iter(cs), transform, workers=2, depth=4)
    assert float(np.asarray(next(pf))[0, 0]) == 0.0
    assert float(np.asarray(next(pf))[0, 0]) == 1.0
    with pytest.raises(RuntimeError, match="bad chunk"):
        next(pf)
    assert not _alive_workers()


def test_transform_failure_under_tight_budget_refunds():
    """Regression: a post-admission failure must refund the HBM budget and
    advance the admission cursor, or successors stall against a phantom
    occupant. With budget=1 every chunk needs a full refund cycle."""
    def transform(c):
        if float(c[0, 0]) == 1.0:
            raise RuntimeError("bad chunk")
        return c

    cs = [np.full((8, 8), float(i), np.float32) for i in range(6)]
    pf = ChunkPrefetcher(iter(cs), transform, workers=3, depth=4,
                         hbm_budget_bytes=1)
    assert float(np.asarray(next(pf))[0, 0]) == 0.0
    with pytest.raises(RuntimeError, match="bad chunk"):
        next(pf)
    assert not _alive_workers()


def test_close_midstream_joins_threads():
    pf = ChunkPrefetcher(iter(_chunks(50)), workers=3, depth=2)
    next(pf)
    pf.close()
    assert not _alive_workers()
    with pytest.raises(StopIteration):
        next(pf)  # closed pipeline is exhausted, not wedged
    pf.close()  # idempotent


def test_context_manager_abandon():
    with ChunkPrefetcher(iter(_chunks(30)), workers=2) as pf:
        next(pf)
    assert not _alive_workers()


def test_exhaustion_closes_automatically():
    pf = ChunkPrefetcher(iter(_chunks(4)))
    assert len(list(pf)) == 4
    assert not _alive_workers()


def test_bad_knobs_rejected():
    with pytest.raises(ValueError):
        ChunkPrefetcher(iter([]), depth=0)
    with pytest.raises(ValueError):
        ChunkPrefetcher(iter([]), workers=0)


# ------------------------------------------------------------------- chaos
def test_chaos_delayed_producer_still_correct():
    cs = _chunks(6)
    with faults.injected("prefetch.produce",
                         faults.DelayFault(0.05, match="chunk-2")):
        got = list(ChunkPrefetcher(iter(cs), workers=2, depth=3))
    for g, c in zip(got, cs):
        np.testing.assert_array_equal(np.asarray(g), c)


def test_chaos_raising_producer_propagates():
    cs = _chunks(8)
    with faults.injected("prefetch.produce",
                         faults.RaiseFault(match="chunk-3")) as f:
        pf = ChunkPrefetcher(iter(cs), workers=2, depth=4)
        got = [next(pf), next(pf), next(pf)]
        with pytest.raises(faults.FaultInjected):
            next(pf)
    assert f.fired == 1
    assert len(got) == 3
    assert not _alive_workers()


def test_chaos_through_streamed_gramian():
    """The fault surfaces through the public streamed op, and the op's
    worker threads still shut down."""
    a = np.random.default_rng(3).standard_normal((256, 8)).astype(np.float32)
    with faults.injected("prefetch.produce", faults.RaiseFault(match="chunk-1")):
        with pytest.raises(faults.FaultInjected):
            mt.streamed_gramian(a, chunk_rows=64, prefetch=True)
    assert not _alive_workers()


# -------------------------------------------------------------- equivalence
def test_streamed_matmul_equivalence_on_off(compile_count):
    """Prefetch on/off is bit-for-bit equivalent, and streaming traffic is
    compile-bounded: once both paths have run, further streamed multiplies
    add ZERO XLA compiles — the same compile-bound guard the serving suite
    uses (tests/conftest.py compile_count)."""
    rng = np.random.default_rng(7)
    a = rng.standard_normal((640, 24)).astype(np.float32)
    b = rng.standard_normal((24, 8)).astype(np.float32)
    off = mt.streamed_matmul(a, b, chunk_rows=100, prefetch=False)
    on = mt.streamed_matmul(a, b, chunk_rows=100, prefetch=True)
    np.testing.assert_array_equal(on, off)  # bit-for-bit, not allclose
    with compile_count() as c:
        again = mt.streamed_matmul(a, b, chunk_rows=100, prefetch=True)
    np.testing.assert_array_equal(again, off)
    assert c.count == 0, \
        f"a warm streamed multiply recompiled ({c.count} programs)"


def test_streamed_gramian_equivalence_on_off():
    a = np.random.default_rng(8).standard_normal((512, 16)).astype(np.float32)
    on = mt.streamed_gramian(a, chunk_rows=96, prefetch=True)
    off = mt.streamed_gramian(a, chunk_rows=96, prefetch=False)
    np.testing.assert_array_equal(on, off)


def test_equivalence_with_transfer_compression():
    """bf16 transfer compression composes with prefetch: the host-side cast
    moves to producer threads, the math is unchanged."""
    a = np.random.default_rng(9).standard_normal((256, 12)).astype(np.float32)
    on = mt.streamed_gramian(a, chunk_rows=64, transfer_dtype="bfloat16",
                             prefetch=True)
    off = mt.streamed_gramian(a, chunk_rows=64, transfer_dtype="bfloat16",
                              prefetch=False)
    np.testing.assert_array_equal(on, off)


def test_out_of_core_ops_equivalence():
    rng = np.random.default_rng(10)
    big = rng.standard_normal((800, 16)).astype(np.float32)
    b = rng.standard_normal((16, 4)).astype(np.float32)
    ooc = mt.OutOfCoreMatrix(big, chunk_rows=128)
    np.testing.assert_array_equal(ooc.multiply(b, prefetch=True),
                                  ooc.multiply(b, prefetch=False))
    np.testing.assert_array_equal(ooc.gramian(prefetch=True),
                                  ooc.gramian(prefetch=False))
    assert ooc.sum(prefetch=True) == ooc.sum(prefetch=False)


def test_config_flag_controls_default(monkeypatch):
    """prefetch=None follows config.prefetch_enabled; explicit True overrides."""
    constructed = []
    orig = ChunkPrefetcher

    def spy(*args, **kw):
        constructed.append(1)
        return orig(*args, **kw)

    monkeypatch.setattr("marlin_tpu.parallel.streaming.ChunkPrefetcher", spy)
    a = np.ones((64, 4), np.float32)
    with mt.config_context(prefetch_enabled=False):
        mt.streamed_gramian(a, chunk_rows=32)
        assert not constructed
        mt.streamed_gramian(a, chunk_rows=32, prefetch=True)
        assert len(constructed) == 1


def test_empty_stream_still_raises():
    b = np.ones((4, 4), np.float32)
    for prefetch in (True, False):
        with pytest.raises(ValueError, match="empty input stream"):
            mt.streamed_matmul(iter([]), b, prefetch=prefetch)
        with pytest.raises(ValueError, match="empty input stream"):
            mt.streamed_gramian(iter([]), prefetch=prefetch)
    assert not _alive_workers()


# ---------------------------------------------------------- instrumentation
def test_stage_times_recorded():
    a = np.random.default_rng(11).standard_normal((512, 8)).astype(np.float32)
    st = StageTimes()
    mt.streamed_gramian(a, chunk_rows=64, prefetch=True, stats=st)
    for stage in ("produce", "transfer", "stall", "compute", "drain"):
        assert stage in st.seconds, f"missing stage {stage}: {st.summary()}"
    assert st.counts["produce"] == 8  # one per chunk
    assert st.counts["drain"] == 1   # only the n×n result leaves


def test_prefetch_summary_event_in_eventlog(tmp_path):
    from marlin_tpu.utils.tracing import EventLog, set_default_event_log

    log = EventLog(str(tmp_path / "events.jsonl"))
    prev = set_default_event_log(log)
    try:
        a = np.ones((128, 4), np.float32)
        mt.streamed_gramian(a, chunk_rows=32, prefetch=True)
    finally:
        set_default_event_log(prev)
        log.close()
    kinds = [e["kind"] for e in log.read()]
    assert "prefetch" in kinds
    ev = next(e for e in log.read() if e["kind"] == "prefetch")
    assert ev["chunks"] == 4
    assert "produce_s" in ev and "stall_s" in ev


# ------------------------------------------------------------- io loaders
def test_mnist_chunked_loader_matches_bulk(tmp_path):
    from marlin_tpu.io import mnist

    rng = np.random.default_rng(12)
    imgs = rng.integers(0, 256, (50, 7, 7), dtype=np.uint8)
    path = tmp_path / "images-idx3-ubyte"
    with open(path, "wb") as f:
        f.write(struct.pack(">IIII", 2051, 50, 7, 7))
        f.write(imgs.tobytes())

    bulk = mnist.load_mnist_images(str(path))
    chunks = list(mnist.iter_mnist_image_chunks(str(path), chunk_rows=16))
    assert [c.shape[0] for c in chunks] == [16, 16, 16, 2]
    np.testing.assert_array_equal(np.concatenate(chunks), bulk)

    ooc = mnist.mnist_images_out_of_core(str(path), chunk_rows=16)
    assert ooc.shape == (50, 49)
    # streamed gramian over the file-backed source (prefetch on by default)
    np.testing.assert_allclose(ooc.gramian(), bulk.T @ bulk,
                               rtol=1e-4, atol=1e-4)


def test_mnist_truncated_file_fails_loudly(tmp_path):
    from marlin_tpu.io import mnist

    path = tmp_path / "trunc-idx3-ubyte"
    with open(path, "wb") as f:
        f.write(struct.pack(">IIII", 2051, 10, 4, 4))
        f.write(b"\x00" * (3 * 16))  # only 3 of 10 rows
    with pytest.raises(ValueError, match="truncated"):
        list(mnist.iter_mnist_image_chunks(str(path), chunk_rows=4))


def test_text_chunked_loader_rejects_gapped_and_malformed(tmp_path):
    from marlin_tpu.io import iter_matrix_file_chunks

    gapped = tmp_path / "gapped.txt"
    gapped.write_text("0:1.0,2.0\n2:3.0,4.0\n4:5.0,6.0\n")
    with pytest.raises(ValueError, match="contiguous"):
        list(iter_matrix_file_chunks(str(gapped), chunk_rows=2))

    no_colon = tmp_path / "bad.txt"
    no_colon.write_text("0:1.0,2.0\n1.0 2.0\n")
    with pytest.raises(ValueError, match="not row format"):
        list(iter_matrix_file_chunks(str(no_colon), chunk_rows=2))

    bad_idx = tmp_path / "badidx.txt"
    bad_idx.write_text("zero:1.0,2.0\n")
    with pytest.raises(ValueError, match="non-integer row index"):
        list(iter_matrix_file_chunks(str(bad_idx), chunk_rows=2))


def test_text_out_of_core_loader(tmp_path, mesh):
    from marlin_tpu.io import load_matrix_file_out_of_core, save_matrix

    rng = np.random.default_rng(13)
    arr = rng.standard_normal((17, 5)).astype(np.float32)
    path = str(tmp_path / "m.txt")
    save_matrix(mt.DenseVecMatrix.from_array(arr, mesh), path)

    ooc = load_matrix_file_out_of_core(path, chunk_rows=4)
    assert ooc.shape == (17, 5)
    np.testing.assert_allclose(ooc.multiply(np.eye(5, dtype=np.float32)), arr,
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(ooc.gramian(), arr.T @ arr,
                               rtol=1e-3, atol=1e-3)


# --------------------------------------------------------------- CPU smoke
def test_smoke_tiny_streamed_gramian_prefetch_cpu():
    """Fast tier-1 smoke: the full prefetch path (threads, device_put, jit
    accumulate, D2H drain) on a matrix small enough for any CPU run."""
    a = np.random.default_rng(14).standard_normal((96, 6)).astype(np.float32)
    g = mt.streamed_gramian(a, chunk_rows=32, prefetch=True)
    np.testing.assert_allclose(g, a.T @ a, rtol=1e-4, atol=1e-4)
    assert not _alive_workers()
