"""Performance-introspection suite (obs/perf.py + its integrations).

The load-bearing tests: roofline math against fake ``cost_analysis()``
dicts (zero-time and zero-flop are *results*, not crashes), the flight
recorder dumping a parseable black box when the existing
``serve.decode_step`` fault point fires, the single-flight guarantee of
``/debug/profile`` (second concurrent request gets 409 — two overlapping
jax.profiler traces corrupt each other), the upgraded ``/healthz``
readiness states, and the bench regression gate firing on the checked-in
seeded fixture while passing the clean pair.
"""

import json
import os
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from marlin_tpu.config import config_context
from marlin_tpu.obs import perf
from marlin_tpu.obs.exposition import (MetricsServer, health_payload,
                                       register_health_provider,
                                       unregister_health_provider)
from marlin_tpu.obs.metrics import MetricsRegistry
from marlin_tpu.obs.perf import (FlightRecorder, ProgramCosts, program_key,
                                 roofline)
from marlin_tpu.obs.report import analyze, load_events
from marlin_tpu.utils import faults
from marlin_tpu.utils.faults import RaiseFault
from marlin_tpu.utils.tracing import EventLog, set_default_event_log

HEADS = 2


@pytest.fixture()
def default_log(tmp_path):
    log = EventLog(str(tmp_path / "events.jsonl"))
    prev = set_default_event_log(log)
    yield log
    set_default_event_log(prev)
    log.close()


@pytest.fixture(scope="module")
def lm_params():
    from marlin_tpu.models import TransformerLM

    return TransformerLM(vocab=32, d_model=16, heads=HEADS, layers=2,
                         seed=9).init_params()


# ------------------------------------------------------------- roofline math


def test_roofline_compute_bound():
    # intensity 100 F/B, bw bound 1e9*100 = 1e11 > peak 1e10 -> compute bound
    r = roofline(flops=1e8, bytes_accessed=1e6, seconds=0.1,
                 peak_flops=1e10, peak_bw=1e9)
    assert r["achieved_flops_per_s"] == pytest.approx(1e9)
    assert r["attainable_flops_per_s"] == pytest.approx(1e10)
    assert r["roofline_frac"] == pytest.approx(0.1)


def test_roofline_bandwidth_bound():
    # intensity 0.1 F/B: attainable = bw * intensity = 1e8 << peak flops
    r = roofline(flops=1e6, bytes_accessed=1e7, seconds=0.01,
                 peak_flops=1e12, peak_bw=1e9)
    assert r["attainable_flops_per_s"] == pytest.approx(1e8)
    assert r["roofline_frac"] == pytest.approx(1.0)  # achieved == attainable


def test_roofline_zero_time_and_zero_flops():
    # zero/None time: no measurement, never a ZeroDivisionError
    for sec in (0, 0.0, None):
        r = roofline(1e9, 1e6, sec, 1e12, 1e9)
        assert r["achieved_flops_per_s"] is None
        assert r["roofline_frac"] is None
    # zero-FLOP program (pure transfer): bandwidth roofline
    r = roofline(0, 1e6, 0.001, 1e12, 1e9)
    assert r["achieved_flops_per_s"] is None
    assert r["achieved_bytes_per_s"] == pytest.approx(1e9)
    assert r["roofline_frac"] == pytest.approx(1.0)
    # zero flops AND zero bytes: nothing to say
    r = roofline(0, 0, 0.001, 1e12, 1e9)
    assert r["roofline_frac"] is None
    # flops but no peaks known: fraction stays unreported
    r = roofline(1e9, 1e6, 0.1, None, None)
    assert r["achieved_flops_per_s"] == pytest.approx(1e10)
    assert r["roofline_frac"] is None


def test_roofline_not_clamped():
    # achieved > attainable surfaces as frac > 1 (a wrong peak table is
    # worth seeing, not hiding)
    r = roofline(1e12, 1e6, 0.1, 1e12, None)
    assert r["roofline_frac"] == pytest.approx(10.0)


def test_peak_rates_config_override():
    with config_context(obs_peak_flops=5e12, obs_peak_bw=7e11):
        assert perf.peak_rates() == (5e12, 7e11)
    pf, bw = perf.peak_rates()  # CPU detection: nominal but present
    assert pf and pf > 0 and bw and bw > 0


# ------------------------------------------------------------- program costs


def test_program_costs_fake_cost_dict(default_log):
    costs = ProgramCosts()
    key = program_key(bucket="8x4", rows=4, dtype="float32")
    assert key == "bucket=8x4 rows=4 dtype=float32"
    snap = costs.capture("prog", key,
                         cost={"flops": 1000.0, "bytes accessed": 500.0},
                         log=default_log)
    assert snap["flops"] == 1000.0 and snap["bytes"] == 500.0
    assert costs.has("prog", key)
    costs.observe("prog", key, seconds=0.002, calls=4)
    with config_context(obs_peak_flops=1e7, obs_peak_bw=1e9):
        (row,) = costs.rows()
    assert row["calls"] == 4 and row["seconds_per_call"] == 0.0005
    assert row["achieved_flops_per_s"] == pytest.approx(2e6)
    assert row["roofline_frac"] == pytest.approx(0.2)
    # cost record landed exactly once (second capture is a no-op event-wise)
    costs.capture("prog", key, cost={"flops": 1000.0})
    recs = [r for r in default_log.read() if r["kind"] == "program"]
    assert [r["ev"] for r in recs] == ["cost"]


def test_program_costs_zero_flop_and_unmeasured():
    costs = ProgramCosts()
    costs.capture("xfer", "k", cost={"flops": 0.0, "bytes accessed": 1e6})
    costs.observe("xfer", "k", seconds=0.001)
    costs.capture("never_timed", "k", cost={"flops": 5.0})
    with config_context(obs_peak_flops=1e12, obs_peak_bw=1e9):
        rows = {r["program"]: r for r in costs.rows()}
    assert rows["xfer"]["roofline_frac"] == pytest.approx(1.0)  # bw roofline
    assert rows["never_timed"]["roofline_frac"] is None
    assert rows["never_timed"]["calls"] == 0


def test_program_costs_capture_real_lowered_and_render():
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda a, b: a @ b)
    a, b = jnp.ones((64, 32)), jnp.ones((32, 16))
    reg = MetricsRegistry()
    costs = ProgramCosts()
    key = program_key(m=64, k=32, n=16)
    snap = costs.capture("mm", key, lowered=f.trace(a, b).lower())
    assert snap is not None and snap["flops"] == pytest.approx(2 * 64 * 32 * 16)
    costs.observe("mm", key, 0.001)
    reg.add_collector(lambda: costs.collect(reg))
    text = reg.render()
    assert 'marlin_program_flops{program="mm",key="m=64 k=32 n=16"}' in text
    assert "marlin_program_achieved_flops_per_s" in text
    assert "marlin_program_roofline_frac" in text


def test_program_costs_capture_never_raises():
    class Boom:
        def cost_analysis(self):
            raise RuntimeError("no analysis on this backend")

    costs = ProgramCosts()
    assert costs.capture("p", "k", lowered=Boom()) is None
    assert not costs.has("p", "k")
    # ...but the ATTEMPT is recorded: hot-path capture sites gate on
    # tried(), so a backend without cost_analysis() pays the trace exactly
    # once, never once per dispatch
    assert costs.tried("p", "k")
    assert not costs.tried("p", "other")


def test_capture_sites_do_not_retry_failed_traces(lm_params):
    """A bucket whose trace fails must not be re-traced on every dispatch:
    capture_bucket_costs marks the attempt even when the lowering path
    raises (simulated via params the slab derivation chokes on)."""
    from marlin_tpu.serving.batcher import (bucket_program_key,
                                            capture_bucket_costs)

    bad_params = {"emb": np.zeros((4, 4), np.float32)}  # no l0: trace dies
    capture_bucket_costs(bad_params, HEADS, (8, 4), 4)
    key = bucket_program_key(bad_params, (8, 4), 4)
    costs = perf.get_program_costs()
    assert costs.tried("lm_decode_rows", key)
    assert not costs.has("lm_decode_rows", key)


def test_program_emit_and_report_table(default_log):
    costs = ProgramCosts()
    key = program_key(bucket="8x4")
    costs.capture("lm_decode_rows", key,
                  cost={"flops": 4000.0, "bytes accessed": 1000.0},
                  log=default_log)
    costs.observe("lm_decode_rows", key, seconds=0.004, calls=8)
    with config_context(obs_peak_flops=1e7, obs_peak_bw=1e9):
        assert costs.emit(log=default_log) == 1
    out = analyze(default_log.read())
    assert "== program utilization ==" in out
    assert "lm_decode_rows" in out
    # achieved = 4000 / 0.0005 = 8 MFLOP/s = 0.01 GFLOP/s; frac = 0.8
    assert "80.00%" in out


# ---------------------------------------------------------- serving roofline


def test_warmup_captures_and_steps_join(lm_params, default_log):
    """The tentpole integration: warmup captures the bucket cost models,
    live decode steps join their wall times, and the global registry renders
    marlin_program_roofline_frac for the active bucket."""
    from marlin_tpu import obs
    from marlin_tpu.serving import Request, ServeEngine
    from marlin_tpu.serving.kvpool import paged_program_key

    with obs.MetricsServer(port=0) as srv:
        with ServeEngine(lm_params, HEADS, buckets=((8, 4),), max_batch=4,
                         max_wait_ms=0.0, queue_depth=32) as eng:
            eng.warmup()
            key = paged_program_key(lm_params, (8, 4), 4, eng._page_len)
            assert perf.get_program_costs().has("lm_decode_paged", key)
            assert perf.get_program_costs().has("lm_prefill_paged", key)
            hs = [eng.submit(Request(prompt=[1, 2, 3], steps=3))
                  for _ in range(4)]
            eng.drain()
            assert all(h.result(timeout=30).ok for h in hs)
            text = urllib.request.urlopen(srv.url, timeout=10).read().decode()
    rows = {(r["program"], r["key"]): r
            for r in perf.get_program_costs().rows()}
    row = rows[("lm_decode_paged", key)]
    assert row["calls"] >= 1 and row["flops"] > 0
    assert row["roofline_frac"] is not None  # CPU nominal peaks exist
    assert "marlin_program_roofline_frac{" in text
    # engine close emitted util snapshots: the analyzer's table works from
    # the JSONL alone
    out = analyze(default_log.read())
    assert "== program utilization ==" in out and "lm_decode_paged" in out


# ------------------------------------------------------------ flight recorder


def test_flight_ring_bounded_and_ordered():
    fr = FlightRecorder(maxlen=8, name="t")
    for i in range(20):
        fr.record("step", i=i)
    recs = fr.records()
    assert len(recs) == len(fr) == 8
    assert [r["i"] for r in recs] == list(range(12, 20))
    assert all(r["kind"] == "flight" and r["src"] == "t" for r in recs)


def test_flight_ring_concurrency_stress():
    """Writers and snapshot readers race freely: no exception, no torn
    record, the ring stays bounded."""
    fr = FlightRecorder(maxlen=64, name="stress")
    stop = threading.Event()
    errors = []

    def write(tid):
        try:
            for i in range(500):
                fr.record("step", tid=tid, i=i)
        except Exception as e:  # pragma: no cover - the failure under test
            errors.append(e)

    def read():
        try:
            while not stop.is_set():
                for r in fr.records():
                    assert r["kind"] == "flight"
                _ = len(fr)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    writers = [threading.Thread(target=write, args=(t,)) for t in range(4)]
    readers = [threading.Thread(target=read) for _ in range(2)]
    for t in readers + writers:
        t.start()
    for t in writers:
        t.join()
    stop.set()
    for t in readers:
        t.join()
    assert not errors
    assert len(fr) == 64


def test_flight_dump_and_prune(tmp_path, default_log):
    with config_context(obs_profile_dir=str(tmp_path)):
        fr = FlightRecorder(maxlen=4, name="dumpme")
        assert fr.dump(reason="empty") is None  # empty ring: no file
        fr.record("step", i=1, seconds=0.01)
        path = fr.dump(reason="test")
    assert path and os.path.exists(path)
    recs, skipped = load_events(path)
    assert skipped == 0 and recs[0]["ev"] == "step"
    assert recs[0]["reason"] == "test"
    dump_ev = [r for r in default_log.read() if r["kind"] == "flight"]
    assert dump_ev and dump_ev[0]["path"] == path


def test_flight_dump_on_decode_step_fault(lm_params, tmp_path, default_log):
    """The black-box acceptance path: an injected serve.decode_step fault
    fails the step's rows AND lands a flight-recorder JSONL dump whose
    records obs.report parses."""
    from marlin_tpu.serving import Request, ServeEngine

    with config_context(obs_profile_dir=str(tmp_path)):
        eng = ServeEngine(lm_params, HEADS, buckets=((8, 4),), max_batch=4,
                          max_wait_ms=0.0, queue_depth=32, start=False)
        try:
            hs = [eng.submit(Request(prompt=[1, 2], steps=3))
                  for _ in range(2)]
            with faults.injected("serve.decode_step", RaiseFault(times=1)):
                eng.start()
                for h in hs:
                    r = h.result(timeout=60)
                    assert r.status == "error"
            ok = eng.submit(Request(prompt=[3], steps=2))
            assert ok.result(timeout=60).ok  # engine keeps serving
        finally:
            eng.close()
    dumps = [r for r in default_log.read()
             if r["kind"] == "flight" and r.get("ev") == "dump"
             and r.get("reason") == "decode-step-failed"]
    assert dumps, "no flight dump landed for the injected decode fault"
    path = dumps[0]["path"]
    recs, skipped = load_events(path)
    assert skipped == 0 and recs
    # the ring shows the fault itself plus the iterations leading up to it
    evs = {r["ev"] for r in recs}
    assert "decode_fault" in evs and "prefill" in evs
    assert all(r["kind"] == "flight" for r in recs)
    analyze(recs)  # parseable by the analyzer, end to end


# ------------------------------------------------------------------ /healthz


def test_health_payload_states():
    register_health_provider("t-accepting", lambda: {"state": "accepting"})
    try:
        code, body = health_payload()
        assert code == 200 and body["status"] == "ok"
        register_health_provider("t-draining", lambda: {"state": "draining"})
        code, body = health_payload()
        assert code == 503 and body["status"] == "unavailable"
        unregister_health_provider("t-draining")

        def broken():
            raise RuntimeError("probe died")

        register_health_provider("t-broken", broken)
        code, body = health_payload()
        assert code == 503
        assert any(e["state"] == "error" for e in body["engines"])
    finally:
        for n in ("t-accepting", "t-draining", "t-broken"):
            unregister_health_provider(n)


def test_healthz_reports_live_engine_and_503_when_draining(lm_params):
    from marlin_tpu.serving import ServeEngine

    with MetricsServer(port=0) as srv:
        base = f"http://127.0.0.1:{srv.port}"
        eng = ServeEngine(lm_params, HEADS, buckets=((8, 4),), max_batch=4,
                          max_wait_ms=0.0, queue_depth=8)
        try:
            body = urllib.request.urlopen(base + "/healthz",
                                          timeout=10).read().decode()
            payload = json.loads(body)
            mine = [e for e in payload["engines"]
                    if e["name"] == eng._name]
            assert mine and mine[0]["state"] == "accepting"
            assert "live_slots" in mine[0] and "queue_depth" in mine[0]
            # the 503 leg, deterministic: flip the state the provider reads
            eng._state = "draining"
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(base + "/healthz", timeout=10)
            assert exc.value.code == 503
            eng._state = "running"
        finally:
            eng.close()
        # terminal close unregistered the provider: healthz recovers
        code, payload = health_payload()
        assert all(e.get("name") != eng._name for e in payload["engines"])


def test_healthz_503_after_worker_death(lm_params):
    """A crashed worker (BaseException — not the absorbed per-step
    Exception class) must flip the engine out of 'accepting': the probe the
    readiness upgrade exists for is exactly 'stop routing to an engine
    that cannot make progress'."""
    from marlin_tpu.serving import Request, ServeEngine

    eng = ServeEngine(lm_params, HEADS, buckets=((8, 4),), max_batch=4,
                      max_wait_ms=0.0, queue_depth=16, start=False)
    try:
        hs = [eng.submit(Request(prompt=[1, 2], steps=3)) for _ in range(2)]
        with faults.injected("serve.decode_step",
                             RaiseFault(exc=KeyboardInterrupt, times=1)):
            eng.start()
            for h in hs:
                assert h.result(timeout=60).status == "error"
        eng._thread.join(timeout=30)
        assert not eng._thread.is_alive()
        code, body = health_payload()
        mine = [e for e in body["engines"] if e.get("name") == eng._name]
        assert code == 503 and mine and mine[0]["state"] == "closed"
    finally:
        eng.close()


def test_engine_heartbeat_ages(lm_params):
    from marlin_tpu.serving import Request, ServeEngine

    with ServeEngine(lm_params, HEADS, buckets=((8, 4),), max_batch=4,
                     max_wait_ms=0.0, queue_depth=8) as eng:
        h = eng.submit(Request(prompt=[1, 2], steps=2))
        assert h.result(timeout=30).ok
        info = eng._health_info()
        assert info["worker_started"]
        assert info["heartbeat_age_s"] is not None
        assert info["heartbeat_age_s"] >= 0


# ------------------------------------------------------------- debug HTTP


def test_debug_profile_single_flight_409(tmp_path):
    """Second concurrent /debug/profile gets 409 (single-flight), and the
    capture lands an artifact + kind="profile" record once free."""
    with config_context(obs_profile_dir=str(tmp_path)):
        with MetricsServer(port=0) as srv:
            base = f"http://127.0.0.1:{srv.port}"
            assert perf._profile_lock.acquire(blocking=False)
            try:  # a capture "in flight": the next request must 409
                with pytest.raises(urllib.error.HTTPError) as exc:
                    urllib.request.urlopen(base + "/debug/profile?seconds=0",
                                           data=b"", timeout=10)
                assert exc.value.code == 409
            finally:
                perf._profile_lock.release()
            body = urllib.request.urlopen(
                base + "/debug/profile?seconds=0.05", data=b"",
                timeout=60).read().decode()
            out = json.loads(body)
            assert os.path.isdir(out["path"])
            assert out["path"].startswith(str(tmp_path))
            for bad in ("nope", "nan", "inf"):  # nan slides past min/max
                with pytest.raises(urllib.error.HTTPError) as exc:
                    urllib.request.urlopen(
                        base + f"/debug/profile?seconds={bad}",
                        data=b"", timeout=10)
                assert exc.value.code == 400, bad


def test_capture_profile_programmatic(tmp_path, default_log):
    path = perf.capture_profile(seconds=0.0, logdir=str(tmp_path))
    assert os.path.isdir(path)
    recs = [r for r in default_log.read() if r["kind"] == "profile"]
    assert recs and recs[0]["path"] == path


def test_debug_flight_endpoint(lm_params):
    from marlin_tpu.serving import Request, ServeEngine

    with MetricsServer(port=0) as srv:
        base = f"http://127.0.0.1:{srv.port}"
        with ServeEngine(lm_params, HEADS, buckets=((8, 4),), max_batch=4,
                         max_wait_ms=0.0, queue_depth=8) as eng:
            h = eng.submit(Request(prompt=[1, 2], steps=3))
            assert h.result(timeout=30).ok
            body = urllib.request.urlopen(base + "/debug/flight",
                                          timeout=10).read().decode()
        recs = [json.loads(line) for line in body.splitlines() if line]
        mine = [r for r in recs if r.get("src") == eng._name]
        assert mine and any(r["ev"] in ("step", "prefill") for r in mine)


# --------------------------------------------------------------- bench gate


def _gate(base, new):
    from tools.bench_compare import main

    fixtures = os.path.join(os.path.dirname(__file__), "..", "tools",
                            "fixtures")
    return main([os.path.join(fixtures, base), os.path.join(fixtures, new)])


def test_bench_gate_clean_pair_passes(capsys):
    assert _gate("bench_gate_base.json", "bench_gate_clean.json") == 0
    assert "gate passed" in capsys.readouterr().out


def test_bench_gate_fires_on_seeded_regression(capsys):
    assert _gate("bench_gate_base.json", "bench_gate_regressed.json") == 1
    out = capsys.readouterr().out
    assert "GATE FAILED" in out
    assert "2_dense_4000" in out and "REGRESSION" in out
    assert "ttft p50 672->1400 ms" in out  # the TTFT leg fired too
    assert "5_FAILED" in out  # new crash counts as a regression


def test_bench_gate_frac_is_informational():
    from tools.bench_compare import compare

    base = {"serve_decode_roofline": {"config": "serve_decode_roofline",
                                      "value": 0.30, "unit": "frac"}}
    new = {"serve_decode_roofline": {"config": "serve_decode_roofline",
                                     "value": 0.05, "unit": "frac"}}
    rows, regressed = compare(base, new)
    assert not regressed  # utilization explains regressions, never IS one
    assert rows[0][5] == "info"


def test_bench_gate_zero_baseline_still_fires():
    from tools.bench_compare import compare

    base = {"acc": {"config": "acc", "value": 0.0, "unit": "rel err"}}
    worse = {"acc": {"config": "acc", "value": 0.5, "unit": "rel err"}}
    same = {"acc": {"config": "acc", "value": 0.0, "unit": "rel err"}}
    _, regressed = compare(base, worse)
    assert regressed  # any rise off an exact-zero lower-better baseline
    _, regressed = compare(base, same)
    assert not regressed


def test_bench_gate_threshold_override(tmp_path, capsys):
    from tools.bench_compare import compare, load

    fixtures = os.path.join(os.path.dirname(__file__), "..", "tools",
                            "fixtures")
    base = load(os.path.join(fixtures, "bench_gate_base.json"))
    new = load(os.path.join(fixtures, "bench_gate_clean.json"))
    # tighten one config to 1%: the clean pair's -2.2% wobble now trips
    rows, regressed = compare(base, new, tolerance=0.25,
                              thresholds={"2_dense_4000": 0.01})
    assert regressed
    assert [r for r in rows if r[0] == "2_dense_4000"][0][5] == "REGRESSION"


# ----------------------------------------------------------- streamed / tune


def test_streamed_gramian_observes_costs(default_log):
    from marlin_tpu.parallel.streaming import streamed_gramian

    chunks = [np.ones((16, 8), np.float32)] * 3
    streamed_gramian(iter(chunks), prefetch=False)
    rows = [r for r in perf.get_program_costs().rows()
            if r["program"] == "streamed_gramian"
            and "chunk=16x8" in r["key"]]
    assert rows and rows[0]["calls"] >= 3 and rows[0]["flops"] > 0
    utils = [r for r in default_log.read() if r["kind"] == "program"
             and r.get("ev") == "util"
             and r.get("program") == "streamed_gramian"]
    assert utils


def test_autotune_lands_candidate_timings():
    import marlin_tpu as mt
    from marlin_tpu.parallel.autotune import tune_multiply

    mesh = mt.create_mesh()
    a = mt.DenseVecMatrix.random(0, 64, 64, mesh=mesh)
    b = mt.DenseVecMatrix.random(1, 64, 64, mesh=mesh)
    # gspmd + broadcast (rmm's jax.shard_map path is broken at the seed on
    # this jax version — tracked in tier-1's pre-existing failures)
    results = tune_multiply(a, b, strategies=["gspmd", "broadcast"], reps=1)
    assert results
    rows = [r for r in perf.get_program_costs().rows()
            if r["program"] == "multiply" and "shape=64x64x64" in r["key"]]
    strategies = {r["key"].split()[0].split("=")[1] for r in rows}
    assert {"gspmd", "broadcast"} <= strategies
    assert all(r["calls"] >= 1 and r["flops"] == pytest.approx(2 * 64**3)
               for r in rows if r["key"].split()[0].split("=")[1]
               in ("gspmd", "broadcast"))
