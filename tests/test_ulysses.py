"""Ulysses all-to-all attention vs the dense single-device oracle."""

import numpy as np
import pytest

import jax.numpy as jnp

from marlin_tpu.parallel.ring_attention import attention_reference, ring_attention
from marlin_tpu.parallel.ulysses import ulysses_attention


def _qkv(heads, seq, d, seed):
    rng = np.random.default_rng(seed)
    return tuple(
        jnp.asarray(rng.standard_normal((heads, seq, d)).astype(np.float32))
        for _ in range(3)
    )


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_dense(mesh, causal):
    q, k, v = _qkv(4, 64, 16, 0)
    out = ulysses_attention(q, k, v, mesh, causal=causal)
    ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=3e-4, atol=3e-4)


def test_ulysses_uneven_seq(mesh):
    # 51 doesn't divide the axis — pad+mask path must be exact
    q, k, v = _qkv(2, 51, 8, 1)
    out = ulysses_attention(q, k, v, mesh, causal=True)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=3e-4, atol=3e-4)


def test_ulysses_matches_ring(mesh):
    # the two sequence-parallel strategies compute the same exact softmax
    q, k, v = _qkv(2, 96, 16, 2)
    u = ulysses_attention(q, k, v, mesh, causal=True)
    r = ring_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(u), np.asarray(r),
                               rtol=3e-4, atol=3e-4)


def test_ulysses_bf16_precision(mesh):
    q, k, v = _qkv(2, 64, 16, 3)
    out = ulysses_attention(q, k, v, mesh, causal=True, precision="default")
    assert out.dtype == q.dtype
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-2, atol=2e-2)


def test_ulysses_custom_scale(mesh):
    q, k, v = _qkv(2, 32, 8, 4)
    out = ulysses_attention(q, k, v, mesh, scale=0.2)
    ref = attention_reference(q, k, v, scale=0.2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=3e-4, atol=3e-4)


def test_ulysses_grad(mesh):
    # training through the all-to-all strategy: the flash head kernel's
    # custom VJP is the two-pass Pallas recompute backward
    import jax

    q, k, v = _qkv(4, 64, 16, 7)
    gq, gk, gv = jax.grad(
        lambda *a: ulysses_attention(*a, mesh, causal=True).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    rq, rk, rv = jax.grad(
        lambda *a: attention_reference(*a, causal=True).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    for g, r in ((gq, rq), (gk, rk), (gv, rv)):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=3e-4, atol=3e-4)


def test_ulysses_grad_uneven_seq(mesh):
    import jax

    q, k, v = _qkv(2, 51, 8, 8)
    g = jax.grad(
        lambda q_: ulysses_attention(q_, k, v, mesh, causal=True).sum()
    )(q)
    r = jax.grad(
        lambda q_: attention_reference(q_, k, v, causal=True).sum()
    )(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                               rtol=3e-4, atol=3e-4)


def test_ulysses_grad_memory_bounded(mesh):
    # the recompute backward must stay memory-bounded: no full (sp, sp)
    # score tensor may appear in the grad program at any padded length (the
    # Pallas backward rebuilds probabilities per (block, block) tile)
    import re

    import jax

    rows = mesh.shape["rows"]
    # the padded panel must EXCEED the 1024 flash block cap, or the whole
    # panel legitimately runs as one square block and the assertion below
    # is vacuous (r5 review); 2041 pads to 2048 -> 1024-blocks, pad path on
    seq = rows * 1024 + 1024 - 7
    q, k, v = _qkv(rows, seq, 8, 20)
    jaxpr = jax.make_jaxpr(
        lambda q_: jax.grad(
            lambda qq: ulysses_attention(qq, k, v, mesh, causal=True).sum()
        )(q_)
    )(q)
    for m_ in re.finditer(r"f32\[(\d+),(\d+)\]", str(jaxpr)):
        a, b = int(m_.group(1)), int(m_.group(2))
        # square tiles up to the 1024 flash block cap are VMEM-resident
        # kernel tiles; what must never appear is a square tensor beyond the
        # cap — that would be a full score matrix materializing
        assert not (a == b and a > 1024), \
            f"full ({a},{b}) score tensor in the backward program"


def test_ulysses_batched(mesh):
    # (batch, heads, seq, d): leading dims fold into the head split
    rng = np.random.default_rng(21)
    q, k, v = (jnp.asarray(rng.standard_normal((3, 4, 40, 8)).astype(np.float32))
               for _ in range(3))
    out = ulysses_attention(q, k, v, mesh, causal=True)
    assert out.shape == q.shape
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=3e-4, atol=3e-4)


def test_ring_attention_batched(mesh):
    from marlin_tpu.parallel.ring_attention import ring_attention

    rng = np.random.default_rng(22)
    q, k, v = (jnp.asarray(rng.standard_normal((2, 3, 40, 8)).astype(np.float32))
               for _ in range(3))
    out = ring_attention(q, k, v, mesh, causal=True)
    assert out.shape == q.shape
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=3e-4, atol=3e-4)


def test_ulysses_validation(mesh):
    heads_bad = mesh.shape["rows"] + 1  # never divides the rows axis
    q, k, v = _qkv(heads_bad, 32, 8, 5)
    with pytest.raises(ValueError):
        ulysses_attention(q, k, v, mesh)
    q2, k2, v2 = _qkv(2, 32, 8, 6)
    with pytest.raises(ValueError):
        ulysses_attention(q2[0], k2[0], v2[0], mesh)  # 2-D input
    with pytest.raises(ValueError):
        ulysses_attention(q2, k2, v2, mesh, precision="low")
