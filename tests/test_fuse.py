"""Fused (lazy) matrix expressions: pytree-registered matrix types traced
through jax.jit — the RDD-lineage-deferral analog (SURVEY.md §3.1)."""

import numpy as np
import pytest

import jax

import marlin_tpu as mt


@pytest.fixture()
def abc(mesh):
    a = mt.DenseVecMatrix.random(0, 100, 60, mesh=mesh)
    b = mt.DenseVecMatrix.random(1, 60, 80, mesh=mesh)
    c = mt.DenseVecMatrix.random(2, 100, 80, mesh=mesh)
    return a, b, c


def test_fuse_chain_matches_oracle(abc):
    a, b, c = abc

    @mt.fuse
    def chain(a, b, c):
        return a.multiply(b).add(c).multiply(2.0).transpose()

    out = chain(a, b, c)
    ref = 2.0 * (a.to_numpy() @ b.to_numpy() + c.to_numpy()).T
    np.testing.assert_allclose(out.to_numpy(), ref, rtol=1e-4, atol=1e-4)
    assert out.shape == (80, 100)


def test_fuse_single_trace(abc):
    a, b, c = abc
    traces = []

    @mt.fuse
    def chain(a, b, c):
        traces.append(1)
        return a.multiply(b).add(c)

    r1 = chain(a, b, c)
    r2 = chain(a, b, c)  # cache hit: no retrace, no python dispatch chain
    assert len(traces) == 1
    np.testing.assert_allclose(r1.to_numpy(), r2.to_numpy())


def test_fuse_grad_returns_matrix_cotangent(abc):
    a, b, _ = abc

    @mt.fuse
    def loss(a, b):
        return a.multiply(b).sum()

    g = jax.grad(lambda a: loss(a, b))(a)
    assert isinstance(g, mt.DenseVecMatrix)
    assert g.shape == a.shape
    # d(sum(AB))/dA = 1 @ B^T
    ref = np.ones((100, 80), np.float32) @ b.to_numpy().T
    np.testing.assert_allclose(g.to_numpy(), ref, rtol=1e-4, atol=1e-4)


def test_fuse_vector_roundtrip(mesh):
    v = mt.DistributedVector.from_array(np.arange(10, dtype=np.float32), mesh)
    a = mt.DenseVecMatrix.random(3, 7, 10, mesh=mesh)

    @mt.fuse
    def mv(a, v):
        return a.multiply_vector(v)

    out = mv(a, v)
    assert isinstance(out, mt.DistributedVector)
    np.testing.assert_allclose(out.to_numpy(), a.to_numpy() @ v.to_numpy(),
                               rtol=1e-5, atol=1e-5)


def test_fuse_shape_mismatch_raises_at_trace(abc):
    a, b, _ = abc

    @mt.fuse
    def bad(a, b):
        return b.multiply(a)  # (60,80) @ (100,60): inner-dim mismatch

    with pytest.raises(ValueError, match="inner dim"):
        bad(a, b)


def test_fuse_block_matrix(mesh, a4):
    am = mt.BlockMatrix.from_array(a4, mesh)
    bm = mt.BlockMatrix.from_array(a4.T, mesh)

    @mt.fuse
    def f(x, y):
        return x.multiply(y).subtract(x)

    out = f(am, bm)
    np.testing.assert_allclose(out.to_numpy(), a4 @ a4.T - a4,
                               rtol=1e-5, atol=1e-5)


def test_fuse_int_vector(mesh):
    iv = mt.DistributedIntVector.from_array(np.arange(6), mesh)

    @mt.fuse
    def double(v):
        return type(v)(v.data * 2, v._length, v.mesh, v.column_major)

    out = double(iv)
    assert isinstance(out, mt.DistributedIntVector)
    np.testing.assert_array_equal(out.to_numpy(), np.arange(6) * 2)


def test_fuse_grad_cotangent_pads_are_zero(row_mesh):
    # 100 rows on an 8-row-shard mesh pads to 104: the cotangent of a
    # masked-reduction loss must keep the pad region zero, or every
    # downstream sum/norm/update on the gradient is silently wrong
    a = mt.DenseVecMatrix.random(0, 100, 60, mesh=row_mesh)
    b = mt.DenseVecMatrix.random(1, 60, 80, mesh=row_mesh)
    g = jax.grad(lambda a: a.multiply(b).sum())(a)
    g_pad = np.asarray(g.data)
    assert g_pad.shape[0] > 100  # padding actually present
    assert np.all(g_pad[100:] == 0.0), "cotangent pads nonzero"
    # reductions on the gradient agree with the logical oracle
    ref = np.ones((100, 80), np.float32) @ b.to_numpy().T
    np.testing.assert_allclose(float(g.sum()), ref.sum(), rtol=1e-4)
    np.testing.assert_allclose(float(g.norm("fro")),
                               np.linalg.norm(ref), rtol=1e-4)
    # a gradient-descent state update keeps the invariant
    new_a = a.subtract(g.multiply(1e-3))
    np.testing.assert_allclose(float(new_a.sum()),
                               (a.to_numpy() - 1e-3 * ref).sum(), rtol=1e-3)
