"""The static-analysis suite analyzed: every check fires on its seeded
fixture and stays silent on the clean one, the suppression baseline
round-trips, the JSON schema holds, and the real repo passes its own gate.

Stdlib-only on purpose (no jax import): the analyzer must run on a box
that cannot import the package it analyzes, and so must its tests.
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

from tools.analyze.__main__ import FIXTURES, _selftest, main
from tools.analyze.checks import CHECKS, run_checks
from tools.analyze.core import (Finding, Repo, load_baseline, save_baseline,
                                split_by_baseline)

ROOT = Path(__file__).resolve().parents[1]
FDIR = ROOT / "tests" / "fixtures" / "analyze"
TREE = FDIR / "consistency_tree"


def _run_fixture(check: str, fixture: str):
    repo = Repo(FDIR, explicit_files=[FDIR / fixture])
    return run_checks(repo, names=[check])


# ------------------------------------------------------------ per-check

@pytest.mark.parametrize("check,fixture", sorted(FIXTURES.items()))
def test_check_fires_on_seeded_fixture(check, fixture):
    findings = [f for f in _run_fixture(check, fixture) if f.check == check]
    assert findings, f"{check} must fire on {fixture}"
    for f in findings:
        assert f.path == fixture
        assert f.line > 0 and f.message and f.hint and f.key
        assert f.severity == "error"


@pytest.mark.parametrize("check", sorted(FIXTURES))
def test_check_silent_on_clean_fixture(check):
    findings = [f for f in _run_fixture(check, "clean.py")
                if f.check == check]
    assert findings == [], f"{check} must stay silent on clean.py"


def test_lock_discipline_flags_both_unlocked_domains():
    keys = {f.key for f in _run_fixture("lock-discipline", "bad_locks.py")}
    assert keys == {
        "lock-discipline:bad_locks.py:ServeEngine.counter@submit",
        "lock-discipline:bad_locks.py:ServeEngine.counter@_run",
    }


def test_recompile_flags_all_three_hazard_shapes():
    keys = {f.key for f in _run_fixture("recompile", "bad_recompile.py")}
    assert "recompile:bad_recompile.py:make_program@closure" in keys
    assert any(k.startswith("recompile:bad_recompile.py:loop@")
               for k in keys)
    assert "recompile:bad_recompile.py:scaled@traced-knob" in keys


def test_donation_names_the_donated_chain():
    (f,) = _run_fixture("donation", "bad_donation.py")
    assert f.key == "donation:bad_donation.py:train.state@_step"


def test_consistency_tree_finds_every_seeded_drift():
    repo = Repo(TREE)
    keys = {f.key for f in run_checks(repo,
                                      names=["doc-sync", "test-hygiene"])}
    assert keys == {
        "doc-sync:faults:net.flaky@undocumented",
        "doc-sync:faults:fs.phantom@ghost",
        "doc-sync:config:retry_max@default-drift",
        "doc-sync:config:unused_knob@undocumented",
        "doc-sync:config:unused_knob@dead-knob",
        "doc-sync:config:ghost_knob@ghost",
        "doc-sync:config:pagelen@unknown-read:marlin_tpu/engine.py:"
        "configure",
        "doc-sync:metrics:marlin_mini_depth@undocumented",
        "doc-sync:metrics:marlin_mini_ghost@ghost",
        "doc-sync:metrics:marlin_mini_missing_total@bench-want",
        "doc-sync:memory:mystery_comp@undocumented",
        "doc-sync:memory:phantom_comp@ghost",
        "doc-sync:events:kind:mystery@unknown",
        "doc-sync:events:ev:surprise@unknown",
        "doc-sync:events:ev:stale_ev@stale",
        "test-hygiene:marlin_tpu/utils/faults.py:net.flaky@untested",
    }


# ------------------------------------------------------------ annotations

def test_ignore_annotation_suppresses_a_finding(tmp_path):
    (tmp_path / "mod.py").write_text(
        "def decode_step(tokens):\n"
        "    # analyze: ignore[host-sync] — the one intentional pull\n"
        "    return tokens.item()\n")
    repo = Repo(tmp_path, explicit_files=[tmp_path / "mod.py"])
    assert run_checks(repo, names=["host-sync"]) == []


def test_single_writer_annotation_exempts_the_field(tmp_path):
    (tmp_path / "mod.py").write_text(
        "import threading\n\n\n"
        "class ServeEngine:\n"
        "    def __init__(self):\n"
        "        self.hb = 0\n"
        "        self._t = threading.Thread(target=self._run)\n\n"
        "    def poke(self):\n"
        "        # analyze: single-writer — generation-guarded stamp\n"
        "        self.hb = 1\n\n"
        "    def _run(self):\n"
        "        self.hb = 2\n")
    repo = Repo(tmp_path, explicit_files=[tmp_path / "mod.py"])
    assert run_checks(repo, names=["lock-discipline"]) == []


def test_parse_error_is_a_finding_not_a_crash(tmp_path):
    (tmp_path / "broken.py").write_text("def f(:\n")
    repo = Repo(tmp_path, explicit_files=[tmp_path / "broken.py"])
    findings = run_checks(repo, names=["host-sync"])
    assert [f.check for f in findings] == ["parse"]


# ------------------------------------------------------------- baseline

def test_baseline_roundtrip_suppresses_and_prunes(tmp_path):
    findings = _run_fixture("lock-discipline", "bad_locks.py")
    bpath = tmp_path / "baseline.json"
    save_baseline(bpath, findings, "fixture: seeded on purpose")
    baseline = load_baseline(bpath)
    assert all(baseline[f.key] == "fixture: seeded on purpose"
               for f in findings)
    new, suppressed, stale = split_by_baseline(findings, baseline)
    assert new == [] and len(suppressed) == len(findings) and stale == []
    # a baseline key nothing matches any more is reported stale
    baseline["lock-discipline:gone.py:X.y@z"] = "obsolete"
    _, _, stale = split_by_baseline(findings, baseline)
    assert stale == ["lock-discipline:gone.py:X.y@z"]


def test_baseline_missing_file_is_empty(tmp_path):
    assert load_baseline(tmp_path / "nope.json") == {}


# ------------------------------------------------------------------ CLI

def test_cli_exit_one_on_findings_zero_with_baseline(tmp_path, capsys):
    fixture = str(FDIR / "bad_locks.py")
    bpath = tmp_path / "baseline.json"
    assert main([fixture, "--baseline", str(bpath)]) == 1
    assert main([fixture, "--baseline", str(bpath),
                 "--update-baseline", "--reason", "seeded"]) == 0
    assert main([fixture, "--baseline", str(bpath)]) == 0
    capsys.readouterr()


def test_cli_update_baseline_requires_reason(tmp_path, capsys):
    fixture = str(FDIR / "bad_locks.py")
    bpath = tmp_path / "baseline.json"
    assert main([fixture, "--baseline", str(bpath),
                 "--update-baseline"]) == 2
    capsys.readouterr()


def test_cli_json_schema(tmp_path, capsys):
    # bad_locks, not bad_hostsync: host-sync skips files under tests/
    # when resolved repo-relative, lock-discipline runs everywhere
    fixture = str(FDIR / "bad_locks.py")
    main([fixture, "--no-baseline", "--json"])
    payload = json.loads(capsys.readouterr().out)
    assert payload["version"] == 1
    assert payload["findings"], "seeded fixture must produce findings"
    for f in payload["findings"]:
        assert set(f) >= {"check", "path", "line", "message", "hint",
                          "severity", "key"}
    assert "suppressed" in payload and "stale_baseline_keys" in payload


def test_selftest_green():
    assert _selftest(ROOT) == 0


def test_repo_gate_is_green():
    """The shipped tree passes its own strict gate: no non-baselined
    error findings. Run as a subprocess so the gate's real entry point
    (python -m tools.analyze) is what's exercised."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.analyze"], cwd=ROOT,
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_every_check_registered_and_listed(capsys):
    assert set(CHECKS) == {"lock-discipline", "donation", "recompile",
                           "host-sync", "doc-sync", "test-hygiene"}
    assert main(["--list"]) == 0
    out = capsys.readouterr().out
    for name in CHECKS:
        assert name in out
