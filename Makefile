# Repo-level entry points. `make check` is the default: the serving gate
# (tier-1 serving + resilience tests, then tools/bench_compare.py over the
# BENCH_ALL.json serve_* records), the bench-gate selftest, and the
# obs-report smoke — see tools/Makefile for the individual targets and
# their knobs (SERVE_BASE/SERVE_NEW, BASE/NEW).

.DEFAULT_GOAL := check

check:
	$(MAKE) -C tools check

serve-gate:
	$(MAKE) -C tools serve-gate

tier1:
	env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
		--continue-on-collection-errors -p no:cacheprovider

.PHONY: check serve-gate tier1
