# Repo-level entry points. `make check` is the default: the native-library
# build (fails loudly when the toolchain is missing — a silent fallback to
# the pure-Python data plane is a 100x perf bug that looks like a pass),
# then the serving gate (tier-1 serving + resilience tests, then
# tools/bench_compare.py over the BENCH_ALL.json serve_* records), the
# out-of-core data-plane gate, the bench-gate selftest, and the obs-report
# smoke — see tools/Makefile for the individual targets and their knobs
# (SERVE_BASE/SERVE_NEW, OOC_BASE/OOC_NEW, BASE/NEW).

.DEFAULT_GOAL := check

check: native
	$(MAKE) -C tools check

# both native IO libraries (libmarlin_textio.so, libmarlin_chunkstore.so)
native:
	$(MAKE) -C marlin_tpu/native

serve-gate:
	$(MAKE) -C tools serve-gate

ooc-gate:
	$(MAKE) -C tools ooc-gate

obs-gate:
	$(MAKE) -C tools obs-gate

fleet-gate:
	$(MAKE) -C tools fleet-gate

# repo-aware static analysis (tools/analyze; docs/static_analysis.md):
#   make analyze / make analyze-gate
#   make analyze BASELINE=update REASON='why'
analyze:
	$(MAKE) -C tools analyze

analyze-gate:
	$(MAKE) -C tools analyze-gate

# build the .mchunk sidecar for a data file (native binary data plane):
#   make chunkstore SRC=path/to/matrix.txt
# auto-detects text vs idx3 from the name; more knobs via
#   python -m marlin_tpu.io.chunkstore build --help
chunkstore: native
	@test -n "$(SRC)" || { echo "usage: make chunkstore SRC=<file>"; exit 2; }
	env JAX_PLATFORMS=cpu python -c "\
	from marlin_tpu.io.chunkstore import _main; \
	import sys; sys.exit(_main(['build', '$(SRC)']))"

tier1:
	env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
		--continue-on-collection-errors -p no:cacheprovider

.PHONY: check native serve-gate ooc-gate obs-gate analyze analyze-gate \
	chunkstore tier1
